"""Chaos / resilience suite (ISSUE 6 tentpole).

Injects deterministic faults (``HEAT_TPU_FAULT_PLAN`` semantics via
``resilience.arm_fault_plan``) at the five instrumented site families —
collective invocation, executor compile, executor execute (including the
donation-armed case), checkpoint writes, and relay probes — and asserts:

- recovery is **bit-identical** to the fault-free run (retry or eager fallback,
  never silently different numerics);
- the diagnostics counters/events explain what happened (retries, fallbacks,
  breaker transitions, quarantines);
- compiled HLO is **byte-identical** whether or not a fault plan is armed
  (the resilience layer lives strictly outside traced program bodies);
- the policy engine and circuit breaker follow their documented state machines
  under injectable clocks (zero wall-time in tests).
"""

import json
import os
import subprocess
import sys
import types as _pytypes
import unittest.mock as mock
from functools import partial

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import heat_tpu as ht
from heat_tpu.core import _executor, devices, diagnostics, resilience
from heat_tpu.testing import TestCase

_OLD_THRESHOLD = None


def setUpModule():
    # chaos tests assert the production compile-on-first-miss behaviour (the
    # suite conftest raises the warm-up threshold for signature-diverse tests)
    global _OLD_THRESHOLD
    _OLD_THRESHOLD = os.environ.get("HEAT_TPU_JIT_THRESHOLD")
    os.environ["HEAT_TPU_JIT_THRESHOLD"] = "1"
    _executor.reload_env_knobs()


def tearDownModule():
    if _OLD_THRESHOLD is None:
        os.environ.pop("HEAT_TPU_JIT_THRESHOLD", None)
    else:
        os.environ["HEAT_TPU_JIT_THRESHOLD"] = _OLD_THRESHOLD
    _executor.reload_env_knobs()


class _ResilienceCase(TestCase):
    """Isolation: every test starts disarmed with fresh counters/breakers and
    restores the diagnostics switches it flips."""

    def setUp(self):
        resilience.disarm_fault_plan()
        resilience.reset(clear_breakers=True)
        self._was_enabled = diagnostics._enabled
        self._was_tracing = diagnostics._tracing
        diagnostics.reset()

    def tearDown(self):
        resilience.disarm_fault_plan()
        resilience.reset(clear_breakers=True)
        diagnostics._enabled = self._was_enabled
        diagnostics._tracing = self._was_tracing

    @staticmethod
    def _counters():
        with diagnostics._lock:
            return dict(diagnostics._counters)

    @staticmethod
    def _resilience_events():
        with diagnostics._lock:
            return list(diagnostics._resilience_events)


class _FakeClock:
    def __init__(self, t0=0.0):
        self.t = t0

    def __call__(self):
        return self.t

    def sleep(self, dt):
        self.t += dt


# ------------------------------------------------------------------ policy engine
class TestPolicy(_ResilienceCase):
    def test_backoff_sequence_is_deterministic(self):
        pol = resilience.Policy(max_attempts=5, backoff_base=0.5, jitter=0.0)
        sleeps = []
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 4:
                raise OSError("transient")
            return "ok"

        out = pol.run("t.backoff", flaky, sleep=sleeps.append)
        self.assertEqual(out, "ok")
        self.assertEqual(calls["n"], 4)
        self.assertEqual(sleeps, [0.5, 1.0, 2.0])

    def test_exhaustion_reraises_the_original_exception(self):
        pol = resilience.Policy(max_attempts=3, backoff_base=0.1, jitter=0.0)
        sleeps = []
        with self.assertRaisesRegex(ValueError, "boom"):
            pol.run(
                "t.exhaust",
                lambda: (_ for _ in ()).throw(ValueError("boom")),
                sleep=sleeps.append,
            )
        self.assertEqual(sleeps, [0.1, 0.2])  # no sleep after the final attempt
        kinds = [e["kind"] for e in self._resilience_events() if e["site"] == "t.exhaust"]
        self.assertEqual(kinds, ["retry", "retry", "exhausted"])

    def test_deadline_bounds_unlimited_attempts(self):
        pol = resilience.Policy(
            max_attempts=None, backoff_base=10.0, jitter=0.0,
            deadline_s=35.0, max_delay_s=10.0,
        )
        clock = _FakeClock()
        calls = {"n": 0}

        def always_down():
            calls["n"] += 1
            raise TimeoutError("down")

        with self.assertRaises(TimeoutError):
            pol.run("t.deadline", always_down, sleep=clock.sleep, clock=clock)
        # attempts at t=0, 10, 20, 30; the next backoff would cross 35 s
        self.assertEqual(calls["n"], 4)

    def test_non_retryable_exception_propagates_immediately(self):
        pol = resilience.Policy(max_attempts=5, backoff_base=0.1,
                                retry_on=(OSError,))
        calls = {"n": 0}

        def typed():
            calls["n"] += 1
            raise KeyError("not retryable")

        with self.assertRaises(KeyError):
            pol.run("t.typed", typed, sleep=lambda _s: None)
        self.assertEqual(calls["n"], 1)

    def test_unbounded_without_deadline_is_rejected(self):
        with self.assertRaises(ValueError):
            resilience.Policy(max_attempts=None)


# ------------------------------------------------------------------ circuit breaker
class TestCircuitBreaker(_ResilienceCase):
    def test_state_machine(self):
        clock = _FakeClock()
        br = resilience.CircuitBreaker(
            "t.breaker", failure_threshold=2, cooldown_s=60.0, clock=clock
        )
        self.assertEqual(br.state, resilience.CLOSED)
        br.record_failure("one")
        self.assertEqual(br.state, resilience.CLOSED)
        br.record_failure("two")
        self.assertEqual(br.state, resilience.OPEN)
        self.assertFalse(br.allows())  # short-circuit while open
        clock.t += 61.0
        self.assertEqual(br.state, resilience.HALF_OPEN)
        self.assertTrue(br.allows())  # the half-open trial
        br.record_failure("trial failed")
        self.assertEqual(br.state, resilience.OPEN)  # re-open restarts cooldown
        clock.t += 61.0
        self.assertTrue(br.allows())
        br.record_success()
        self.assertEqual(br.state, resilience.CLOSED)
        self.assertEqual(br.snapshot()["opens"], 2)

    def test_transitions_recorded_via_diagnostics(self):
        clock = _FakeClock()
        br = resilience.CircuitBreaker("t.events", failure_threshold=1,
                                       cooldown_s=5.0, clock=clock)
        br.record_failure("down")
        clock.t += 6.0
        br.allows()
        br.record_success()
        details = [
            e["detail"] for e in self._resilience_events()
            if e["site"] == "t.events" and e["kind"] == "breaker"
        ]
        self.assertTrue(any(d.startswith("closed->open") for d in details), details)
        self.assertTrue(any(d.startswith("open->half-open") for d in details), details)
        self.assertTrue(any(d.startswith("half-open->closed") for d in details), details)

    def test_success_resets_consecutive_failures(self):
        br = resilience.CircuitBreaker("t.reset", failure_threshold=2)
        br.record_failure()
        br.record_success()
        br.record_failure()
        self.assertEqual(br.state, resilience.CLOSED)

    def test_half_open_admits_exactly_one_probe_per_window(self):
        clock = _FakeClock()
        br = resilience.CircuitBreaker("t.probe", failure_threshold=1,
                                       cooldown_s=60.0, clock=clock)
        br.record_failure("down")
        clock.t += 61.0
        self.assertEqual(br.state, resilience.HALF_OPEN)
        self.assertTrue(br.allows())      # the ONE trial probe of this window
        self.assertFalse(br.allows())     # everyone else sees it as open
        self.assertFalse(br.allows())
        self.assertTrue(br.snapshot()["half_open_probe_out"])
        br.record_failure("trial failed")  # probe reports: re-open
        self.assertEqual(br.state, resilience.OPEN)
        clock.t += 61.0
        self.assertTrue(br.allows())      # fresh window, fresh single token
        self.assertFalse(br.allows())
        br.record_success()
        self.assertEqual(br.state, resilience.CLOSED)
        self.assertTrue(br.allows())      # closed: everyone passes again
        self.assertTrue(br.allows())

    def test_half_open_vanished_probe_forfeits_after_another_cooldown(self):
        clock = _FakeClock()
        br = resilience.CircuitBreaker("t.vanish", failure_threshold=1,
                                       cooldown_s=30.0, clock=clock)
        br.record_failure("down")
        clock.t += 31.0
        self.assertTrue(br.allows())   # probe holder... who never reports back
        self.assertFalse(br.allows())
        clock.t += 31.0                # a whole cooldown with no verdict
        self.assertTrue(br.allows())   # new window: the token re-grants
        self.assertFalse(br.allows())

    def test_half_open_deadline_failed_trial_releases_the_probe_token(self):
        clock = _FakeClock()
        br = resilience.CircuitBreaker("t.dlprobe", failure_threshold=1,
                                       cooldown_s=60.0, clock=clock)
        br.record_failure("down")
        clock.t += 61.0
        pol = resilience.Policy(max_attempts=3, backoff_base=0.0)

        def trial_whose_request_expired():
            raise resilience.DeadlineExceeded("budget gone mid-trial")

        with pytest.raises(resilience.DeadlineExceeded):
            pol.run("t.dlprobe", trial_whose_request_expired,
                    breaker=br, sleep=lambda s: None, clock=clock)
        # the trial said nothing about the backend: the token is released so
        # the NEXT caller probes now instead of waiting out another cooldown
        self.assertEqual(br.state, resilience.HALF_OPEN)
        self.assertTrue(br.allows())

    def test_half_open_concurrent_threads_get_one_probe(self):
        import threading

        clock = _FakeClock()
        br = resilience.CircuitBreaker("t.herd", failure_threshold=1,
                                       cooldown_s=60.0, clock=clock)
        br.record_failure("down")
        clock.t += 61.0
        barrier = threading.Barrier(16)
        grants = []

        def caller():
            barrier.wait()
            if br.allows():
                grants.append(threading.get_ident())

        threads = [threading.Thread(target=caller) for _ in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30.0)
        self.assertEqual(
            len(grants), 1,
            f"{len(grants)} threads got the half-open probe (thundering herd)",
        )
        # the breaker re-probed a down backend ONCE, not 16 times
        self.assertGreaterEqual(br.snapshot()["short_circuits"], 15)


# ------------------------------------------------------------------ fault plans
class TestFaultPlan(_ResilienceCase):
    def test_fires_on_exact_nth_call_window(self):
        resilience.arm_fault_plan(
            [{"site": "t.site", "on_call": 3, "count": 2, "kind": "raise"}]
        )
        fired = []
        for _ in range(6):
            fired.append(resilience.fault_signal("t.site") is not None)
        self.assertEqual(fired, [False, False, True, True, False, False])

    def test_kinds_raise_their_exception_types(self):
        resilience.arm_fault_plan(
            [
                {"site": "t.raise", "kind": "raise"},
                {"site": "t.timeout", "kind": "timeout"},
                {"site": "t.down", "kind": "backend-down"},
            ]
        )
        with self.assertRaises(resilience.FaultInjected):
            resilience.maybe_fault("t.raise")
        with self.assertRaises(TimeoutError):  # InjectedTimeout is a TimeoutError
            resilience.maybe_fault("t.timeout")
        with self.assertRaises(resilience.InjectedBackendDown):
            resilience.maybe_fault("t.down")

    def test_disarm_restores_zero_cost_gate(self):
        resilience.arm_fault_plan([{"site": "t.site", "kind": "raise"}])
        self.assertTrue(resilience._armed)
        resilience.disarm_fault_plan()
        self.assertFalse(resilience._armed)
        self.assertIsNone(resilience.fault_signal("t.site"))
        self.assertEqual(resilience.fault_plan(), [])

    def test_json_string_and_validation(self):
        resilience.arm_fault_plan(
            '[{"site": "t.json", "on_call": 2, "kind": "torn-write", "fraction": 0.25}]'
        )
        plan = resilience.fault_plan()
        self.assertEqual(plan[0]["site"], "t.json")
        self.assertEqual(plan[0]["fraction"], 0.25)
        for bad in (
            "not json",
            '{"site": "x"}',  # not a list
            '[{"kind": "raise"}]',  # no site
            '[{"site": "x", "kind": "nope"}]',  # unknown kind
            '[{"site": "x", "on_call": 0}]',  # on_call < 1
            '[{"site": "x", "typo": 1}]',  # unknown key
        ):
            with self.assertRaises(ValueError):
                resilience.arm_fault_plan(bad)

    def test_env_plan_arms_at_import(self):
        env = dict(os.environ)
        env.update(
            JAX_PLATFORMS="cpu",
            PALLAS_AXON_POOL_IPS="",
            HEAT_TPU_FAULT_PLAN='[{"site": "e.site", "on_call": 5, "kind": "timeout"}]',
        )
        code = (
            "import importlib.util, os\n"
            "p = os.path.join(%r, 'heat_tpu', 'core', 'resilience.py')\n"
            "spec = importlib.util.spec_from_file_location('_r', p)\n"
            "m = importlib.util.module_from_spec(spec)\n"
            "spec.loader.exec_module(m)\n"
            "assert m._armed and m.fault_plan()[0]['site'] == 'e.site'\n"
            "print('ENV_PLAN_OK')\n"
        ) % (os.path.dirname(os.path.dirname(os.path.abspath(__file__))),)
        proc = subprocess.run(
            [sys.executable, "-c", code], env=env, capture_output=True, text=True,
            timeout=120,
        )
        self.assertEqual(proc.returncode, 0, proc.stderr[-500:])
        self.assertIn("ENV_PLAN_OK", proc.stdout)


# ------------------------------------------------------------------ chaos: collectives
class TestChaosCollective(_ResilienceCase):
    def test_shard_fault_retried_bit_identically(self):
        np_a = np.arange(10, dtype=np.float32)  # ragged at 3 and 8 devices
        baseline = ht.array(np_a, split=0)
        diagnostics.enable()
        resilience.arm_fault_plan(
            [{"site": "comm.shard", "on_call": 1, "kind": "raise"}]
        )
        x = ht.array(np_a, split=0)  # the layout call absorbs the injected fault
        np.testing.assert_array_equal(x.numpy(), baseline.numpy())
        self.assertGreaterEqual(self._counters().get("resilience.retry.comm.shard", 0), 1)

    def test_psum_fault_retried_inside_shard_map(self):
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        comm = ht.get_comm()
        x = jnp.arange(comm.size, dtype=jnp.float32) + 1.0

        def total():
            # a fresh callable per run so shard_map re-traces (the collective
            # hook — and therefore the fault site — runs at trace time)
            fn = shard_map(
                lambda v: comm.psum(v, comm.axis_name),
                mesh=comm.mesh,
                in_specs=P(comm.axis_name),
                out_specs=P(),
            )
            return np.asarray(fn(x))

        expected = total()
        diagnostics.enable()
        resilience.arm_fault_plan(
            [{"site": "comm.psum", "on_call": 1, "kind": "timeout"}]
        )
        np.testing.assert_array_equal(total(), expected)
        self.assertGreaterEqual(self._counters().get("resilience.retry.comm.psum", 0), 1)


# ------------------------------------------------------------------ chaos: executor
class TestChaosExecutor(_ResilienceCase):
    def _chain(self, np_a):
        x = ht.array(np_a, split=0)
        return ((x + 1.0) * 2.0 - 0.5).numpy()

    def test_compile_fault_falls_back_to_eager_bit_identically(self):
        np_a = np.linspace(0.0, 1.0, 11, dtype=np.float32)
        expected = (np_a + 1.0) * 2.0 - 0.5
        _executor.clear_executor_cache()
        diagnostics.enable()
        resilience.arm_fault_plan(
            [{"site": "executor.compile", "on_call": 1, "count": 99, "kind": "raise"}]
        )
        got = self._chain(np_a)
        np.testing.assert_array_equal(got, expected)
        stats = ht.executor_stats()
        self.assertGreaterEqual(stats["eager_fallbacks"], 1)
        self.assertTrue(
            any(c.startswith("fallback.executor.") for c in self._counters()),
            self._counters(),
        )

    def test_transient_execute_fault_recovers_via_retry(self):
        np_a = np.linspace(-1.0, 1.0, 9, dtype=np.float32)
        expected = (np_a + 1.0) * 2.0 - 0.5
        _executor.clear_executor_cache()
        diagnostics.enable()
        resilience.arm_fault_plan(
            [{"site": "executor.execute", "on_call": 1, "count": 1, "kind": "raise"}]
        )
        got = self._chain(np_a)
        np.testing.assert_array_equal(got, expected)
        stats = ht.executor_stats()
        # one retry absorbed the fault: the compiled program ran, no fallback
        self.assertEqual(stats["eager_fallbacks"], 0)
        self.assertGreaterEqual(
            self._counters().get("resilience.retry.executor.execute", 0), 1
        )

    def test_execute_fault_with_pending_donation_no_data_loss(self):
        np_a = np.arange(16, dtype=np.float32)
        _executor.clear_executor_cache()
        resilience.arm_fault_plan(
            [{"site": "executor.execute", "on_call": 1, "count": 99, "kind": "raise"}]
        )
        x = ht.array(np_a, split=0)
        y = x * 2.0
        del x  # the plan becomes the leaf's sole reader: donation is armed
        np.testing.assert_array_equal(y.numpy(), np_a * 2.0)
        stats = ht.executor_stats()
        self.assertGreaterEqual(stats["eager_fallbacks"], 1)
        # the injected failure struck before dispatch: nothing was donated, the
        # eager replay read live buffers — zero bytes counted as donated
        self.assertEqual(stats["donated_bytes"], 0)

    def test_repeated_failures_quarantine_with_explained_reason(self):
        np_a = np.arange(12, dtype=np.float32)
        _executor.clear_executor_cache()
        os.environ["HEAT_TPU_QUARANTINE_AFTER"] = "3"
        _executor.reload_env_knobs()
        try:
            resilience.arm_fault_plan(
                [{"site": "executor.execute", "on_call": 1, "count": 9999, "kind": "raise"}]
            )
            for i in range(4):
                x = ht.array(np_a + i, split=0)
                y = (x + 1.0) * 3.0
                np.testing.assert_array_equal(y.numpy(), (np_a + i + 1.0) * 3.0)
            stats = ht.executor_stats()
            self.assertGreaterEqual(stats["eager_fallbacks"], 3)
            self.assertTrue(stats["quarantined"], stats)
            label, reason = next(iter(stats["quarantined"].items()))
            self.assertIn("FaultInjected", reason)
            self.assertIn("failure 3", reason)
        finally:
            os.environ.pop("HEAT_TPU_QUARANTINE_AFTER", None)
            _executor.reload_env_knobs()
        # quarantined: later identical dispatches take the eager path and stay correct
        x = ht.array(np_a, split=0)
        np.testing.assert_array_equal(((x + 1.0) * 3.0).numpy(), (np_a + 1.0) * 3.0)

# ----------------------------------------------------- chaos: async executor
class TestChaosAsyncExecutor(_ResilienceCase):
    """ISSUE 8: faults firing inside QUEUED executions (single and batched)
    must fall back via the op-by-op replay with no data loss — the scheduler
    thread is not the caller, so the failure contract has to travel through
    the dispatch-done future and the plan's held leaf references."""

    def _sched(self):
        import threading
        import time

        sched = _executor._get_scheduler()
        sched.resume()
        self.assertTrue(sched.wait_idle(30.0))
        return sched, threading, time

    def tearDown(self):
        sched = _executor._dispatch_scheduler
        if sched is not None:
            sched.resume()
            # wait_idle's bool must be checked: a timed-out wait here means a
            # stuck scheduler leaking into every later test
            self.assertTrue(sched.wait_idle(30.0), "scheduler stuck busy")
        super().tearDown()

    def test_fault_inside_queued_execution_replays_eager_no_data_loss(self):
        sched, threading, time = self._sched()
        _executor.clear_executor_cache()
        np_a = np.linspace(-2.0, 2.0, 16, dtype=np.float32)
        x = ht.array(np_a, split=0)
        expected = ((x + 1.0) * 2.0 - 0.5).numpy()  # warm + reference bits
        diagnostics.enable()
        resilience.arm_fault_plan(
            [{"site": "executor.execute", "on_call": 1, "count": 99,
              "kind": "raise"}]
        )
        got = {}
        errors = []

        def force():
            try:
                got["v"] = ((x + 1.0) * 2.0 - 0.5).numpy()
            except Exception as exc:
                errors.append(exc)

        sched.pause()  # the force must park in the queue, not run inline
        try:
            th = threading.Thread(target=force, daemon=True)
            th.start()
            deadline = time.monotonic() + 30.0
            while sched.depth() < 1 and time.monotonic() < deadline:
                time.sleep(0.005)
            self.assertGreaterEqual(sched.depth(), 1, "force never queued")
        finally:
            sched.resume()
        th.join(60.0)
        self.assertFalse(errors, errors)
        np.testing.assert_array_equal(got["v"], expected)
        stats = ht.executor_stats()
        self.assertGreaterEqual(stats["eager_fallbacks"], 1)
        self.assertEqual(stats.get("quarantined", {}), {})

    def test_fault_inside_batched_execution_no_data_loss(self):
        sched, threading, time = self._sched()
        _executor.clear_executor_cache()
        datas = [
            np.linspace(-1.0, 1.0, 16, dtype=np.float32) * (i + 1)
            for i in range(2)
        ]
        arrs = [ht.array(d, split=0) for d in datas]
        expected = [((a * 2.0) + 1.0).numpy() for a in arrs]  # warm, unbatched
        diagnostics.enable()
        got = [None, None]
        errors = []

        def force(i):
            try:
                got[i] = ((arrs[i] * 2.0) + 1.0).numpy()
            except Exception as exc:
                errors.append(exc)

        sched.pause()
        try:
            threads = [
                threading.Thread(target=force, args=(i,), daemon=True)
                for i in range(2)
            ]
            for th in threads:
                th.start()
            deadline = time.monotonic() + 30.0
            while sched.depth() < 2 and time.monotonic() < deadline:
                time.sleep(0.005)
            self.assertGreaterEqual(sched.depth(), 2, "forces never queued")
            # armed only now: the faults fire inside the BATCHED execution
            resilience.arm_fault_plan(
                [{"site": "executor.execute", "on_call": 1, "count": 99,
                  "kind": "raise"}]
            )
        finally:
            sched.resume()
        for th in threads:
            th.join(60.0)
        self.assertFalse(errors, errors)
        for i in range(2):
            np.testing.assert_array_equal(got[i], expected[i])
        stats = ht.executor_stats()
        # the batch degraded to singles, each single to the eager replay
        self.assertGreaterEqual(stats["eager_fallbacks"], 2)
        self.assertTrue(
            any(c.startswith("fallback.executor.") for c in self._counters()),
            self._counters(),
        )


# --------------------------------------------------- chaos: request lifecycle
class TestChaosLifecycle(_ResilienceCase):
    """ISSUE 10: the `deadline-exceeded` fault kind fired inside queued and
    batched executions, plus drain-under-load — in every case each
    outstanding ``PendingValue`` is fulfilled with a value or a TYPED error,
    never stranded, and over-deadline work is never salvaged by the eager
    replay (no quarantine: the signature stays healthy)."""

    def _sched(self):
        import threading
        import time

        sched = _executor._get_scheduler()
        sched.reopen()
        sched.resume()
        self.assertTrue(sched.wait_idle(30.0))
        return sched, threading, time

    def tearDown(self):
        sched = _executor._dispatch_scheduler
        if sched is not None:
            sched.reopen()
            sched.resume()
            self.assertTrue(sched.wait_idle(30.0), "scheduler stuck busy")
        super().tearDown()

    def test_deadline_fault_inside_queued_execution_is_typed_then_retries(self):
        sched, threading, time = self._sched()
        _executor.clear_executor_cache()
        np_a = np.linspace(-2.0, 2.0, 16, dtype=np.float32)
        x = ht.array(np_a, split=0)
        expected = ((x + 1.0) * 2.0 - 0.5).numpy()  # warm + reference bits
        diagnostics.enable()
        outcome = {}

        def force():
            try:
                outcome["v"] = ((x + 1.0) * 2.0 - 0.5).numpy()
            except Exception as exc:
                outcome["err"] = exc

        sched.pause()
        try:
            th = threading.Thread(target=force, daemon=True)
            th.start()
            deadline = time.monotonic() + 30.0
            while sched.depth() < 1 and time.monotonic() < deadline:
                time.sleep(0.005)
            self.assertGreaterEqual(sched.depth(), 1, "force never queued")
            # fires inside the QUEUED execution, exactly once
            resilience.arm_fault_plan(
                [{"site": "executor.execute", "on_call": 1, "count": 1,
                  "kind": "deadline-exceeded"}]
            )
        finally:
            sched.resume()
        th.join(60.0)
        # the reader got the TYPED error — not a hang, not a silent eager
        # replay of over-deadline work
        self.assertIn("err", outcome, outcome)
        self.assertIsInstance(outcome["err"], resilience.DeadlineExceeded)
        stats = ht.executor_stats()
        self.assertGreaterEqual(stats["expired_requests"], 1)
        self.assertEqual(stats["eager_fallbacks"], 0,
                         "over-deadline work must not replay eagerly")
        self.assertEqual(stats.get("quarantined", {}), {},
                         "a deadline expiry is not a signature failure")
        # the fault window has passed: the next force retries cleanly
        np.testing.assert_array_equal(((x + 1.0) * 2.0 - 0.5).numpy(), expected)

    def test_deadline_fault_inside_batched_execution_strands_nothing(self):
        sched, threading, time = self._sched()
        _executor.clear_executor_cache()
        datas = [
            np.linspace(-1.0, 1.0, 16, dtype=np.float32) * (i + 1)
            for i in range(2)
        ]
        arrs = [ht.array(d, split=0) for d in datas]
        expected = [((a * 2.0) + 1.0).numpy() for a in arrs]  # warm, unbatched
        diagnostics.enable()
        got = [None, None]
        errors = []

        def force(i):
            try:
                got[i] = ((arrs[i] * 2.0) + 1.0).numpy()
            except Exception as exc:
                errors.append(exc)

        sched.pause()
        try:
            threads = [
                threading.Thread(target=force, args=(i,), daemon=True)
                for i in range(2)
            ]
            for th in threads:
                th.start()
            deadline = time.monotonic() + 30.0
            while sched.depth() < 2 and time.monotonic() < deadline:
                time.sleep(0.005)
            self.assertGreaterEqual(sched.depth(), 2, "forces never queued")
            # fires once, inside the BATCHED call. The batch degrades to
            # singles; each single re-checks ITS OWN deadline (none armed
            # here), so both requests complete — per-item deadlines are why
            # one item's expiry must never fail a whole batch
            resilience.arm_fault_plan(
                [{"site": "executor.execute", "on_call": 1, "count": 1,
                  "kind": "deadline-exceeded"}]
            )
        finally:
            sched.resume()
        for th in threads:
            th.join(60.0)
        self.assertFalse(errors, errors)
        for i in range(2):
            np.testing.assert_array_equal(got[i], expected[i])
        self.assertEqual(ht.executor_stats().get("quarantined", {}), {})

    def test_drain_under_load_strands_no_future(self):
        sched, threading, time = self._sched()
        _executor.clear_executor_cache()
        datas = [
            np.linspace(-1.0, 1.0, 32, dtype=np.float32) * (i + 1)
            for i in range(6)
        ]
        arrs = [ht.array(d, split=0) for d in datas]
        for a in arrs:
            ((a * 1.5) + 0.5).parray  # warm
        outcomes = [None] * 6

        def force(i):
            try:
                outcomes[i] = ("ok", ((arrs[i] * 1.5) + 0.5).numpy())
            except BaseException as exc:
                outcomes[i] = ("err", exc)

        sched.pause()  # build a queue mid-"load"
        threads = [
            threading.Thread(target=force, args=(i,), daemon=True)
            for i in range(6)
        ]
        for th in threads:
            th.start()
        deadline = time.monotonic() + 30.0
        while sched.depth() < 6 and time.monotonic() < deadline:
            time.sleep(0.005)
        self.assertGreaterEqual(sched.depth(), 6, "forces never queued")
        # drain with a real timeout: lifts the pause, flushes everything
        result = sched.drain(timeout=60.0)
        self.assertTrue(result["flushed"])
        for th in threads:
            th.join(60.0)
        for i, out in enumerate(outcomes):
            self.assertIsNotNone(out, f"reader {i} stranded")
            status, payload = out
            if status == "ok":
                np.testing.assert_allclose(
                    payload, datas[i] * 1.5 + 0.5, rtol=1e-6, atol=1e-6
                )
            else:  # a typed lifecycle error is acceptable; a hang was not
                self.assertIsInstance(
                    payload,
                    (resilience.DrainTimeout, resilience.Shed,
                     resilience.RequestCancelled),
                )
        sched.reopen()

    def test_atexit_drain_settles_queued_futures_in_subprocess(self):
        """Interpreter shutdown with a PAUSED scheduler and a queued force:
        the executor's atexit drain must settle the dispatch-done future
        (value or typed error) and the process must exit cleanly — no hang."""
        script = r"""
import atexit, threading, time
import numpy as np

state = {}

def check():  # registered BEFORE heat_tpu: runs AFTER the executor's drain
    pv = state.get("pending")
    if pv is None:
        print("VERDICT: no-pending")
    elif pv.done():
        print("VERDICT: settled failed=%s" % pv.failed())
    else:
        print("VERDICT: STRANDED")

atexit.register(check)

import heat_tpu as ht
from heat_tpu.core import _executor, _scheduler

sched = _executor._get_scheduler()
sched.pause()
np_a = np.arange(16, dtype=np.float32)
x = ht.array(np_a, split=0)
v = (x + 7.0) * 2.0

def read():
    v.parray  # blocks on the paused queue

t = threading.Thread(target=read, daemon=True)
t.start()
deadline = time.monotonic() + 30.0
while sched.depth() < 1 and time.monotonic() < deadline:
    time.sleep(0.005)
assert sched.depth() >= 1, "force never queued"
pv = v._payload.value
assert isinstance(pv, _scheduler.PendingValue), type(pv)
state["pending"] = pv
print("QUEUED ok")
# main exits here with the scheduler paused: only the atexit drain can
# settle the future
"""
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        proc = subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True, text=True, timeout=120, env=env,
        )
        self.assertEqual(proc.returncode, 0, proc.stderr)
        self.assertIn("QUEUED ok", proc.stdout, proc.stdout)
        self.assertIn("VERDICT: settled", proc.stdout,
                      f"stdout={proc.stdout!r} stderr={proc.stderr[-500:]!r}")


# ------------------------------------------------------------------ chaos: checkpoint
class TestChaosCheckpoint(_ResilienceCase):
    def setUp(self):
        super().setUp()
        import tempfile

        self.tmp = tempfile.mkdtemp()

    def tearDown(self):
        import shutil

        shutil.rmtree(self.tmp, ignore_errors=True)
        super().tearDown()

    def test_transient_write_fault_retried_roundtrip_identical(self):
        diagnostics.enable()
        x = ht.array(np.arange(20, dtype=np.float32).reshape(4, 5), split=0)
        # ISSUE 13: the default save is the parallel chunked v2 path — its
        # writes run under the checkpoint.chunk_write site
        resilience.arm_fault_plan(
            [{"site": "checkpoint.chunk_write", "on_call": 1, "count": 1,
              "kind": "raise"}]
        )
        path = os.path.join(self.tmp, "ckpt")
        ht.save_checkpoint({"x": x}, path)  # attempt 1 injected, attempt 2 lands
        back = ht.load_checkpoint({"x": ht.zeros((4, 5), split=0)}, path)
        self.assert_array_equal(back["x"], x.numpy())
        self.assertGreaterEqual(
            self._counters().get("resilience.retry.checkpoint.chunk_write", 0), 1
        )

    def test_torn_write_rejected_on_restore(self):
        x = ht.array(np.arange(24, dtype=np.float32), split=0)
        resilience.arm_fault_plan(
            [{"site": "checkpoint.chunk_write", "on_call": 1,
              "kind": "torn-write", "fraction": 0.25}]
        )
        path = os.path.join(self.tmp, "torn")
        ht.save_checkpoint({"x": x}, path)  # commits a silently truncated chunk
        with self.assertRaises(ht.CheckpointCorrupt) as ctx:
            ht.load_checkpoint({"x": ht.zeros((24,), split=0)}, path)
        self.assertIn("torn write", str(ctx.exception))
        events = [
            e for e in self._resilience_events()
            if e["site"] == "checkpoint.restore" and e["kind"] == "corrupt"
        ]
        self.assertTrue(events, self._resilience_events())


# ------------------------------------------------------------------ chaos: relay probes
class TestChaosRelayProbe(_ResilienceCase):
    def _fake_proc(self, rc):
        return _pytypes.SimpleNamespace(returncode=rc, stdout=b"", stderr=b"")

    def test_flapping_probes_fold_into_one_outage_window(self):
        import bench

        bench._PROBES.clear()
        sleeps = []
        rcs = iter([1, 1, 0])  # down, down, up
        with mock.patch("subprocess.run", side_effect=lambda *a, **k: self._fake_proc(next(rcs))):
            up = bench._backend_reachable(timeout_s=5.0, attempts=3, sleep=sleeps.append)
        self.assertTrue(up)
        # every policy attempt landed in the probe history EXACTLY once
        self.assertEqual([p["up"] for p in bench._PROBES], [False, False, True])
        self.assertEqual(sleeps, [60.0, 60.0])
        windows = diagnostics.relay_outage_windows(bench._PROBES)
        self.assertEqual(len(windows), 1)
        self.assertIsNotNone(windows[0]["end"])  # the outage closed on the up probe

    def test_all_probes_down_exhausts_and_reports_open_window(self):
        import bench

        bench._PROBES.clear()
        with mock.patch("subprocess.run", side_effect=lambda *a, **k: self._fake_proc(1)):
            up = bench._backend_reachable(timeout_s=5.0, attempts=3, sleep=lambda _s: None)
        self.assertFalse(up)
        self.assertEqual([p["up"] for p in bench._PROBES], [False, False, False])
        windows = diagnostics.relay_outage_windows(bench._PROBES)
        self.assertEqual(len(windows), 1)
        self.assertIsNone(windows[0]["end"])  # still open at round end

    def test_injected_probe_fault_skips_the_subprocess(self):
        import _diag_bootstrap
        import bench

        res = _diag_bootstrap.load_resilience()
        self.assertIsNotNone(res)
        bench._PROBES.clear()
        res.arm_fault_plan(
            [{"site": "probe.relay", "on_call": 1, "count": 99, "kind": "backend-down"}]
        )
        try:
            with mock.patch(
                "subprocess.run",
                side_effect=AssertionError("probe must not spawn a child"),
            ):
                self.assertFalse(bench._probe_backend(timeout_s=5.0))
        finally:
            res.disarm_fault_plan()
            res.reset(clear_breakers=True)
        self.assertEqual([p["up"] for p in bench._PROBES], [False])


# ------------------------------------------------------------------ breaker satellite
class TestCapsProbeBreaker(_ResilienceCase):
    def test_open_relay_breaker_short_circuits_caps_probe(self):
        clock = _FakeClock()
        br = resilience.breaker(
            "backend.relay", failure_threshold=2, cooldown_s=300.0, clock=clock
        )
        br.record_failure("relay probe 1")
        br.record_failure("relay probe 2")
        self.assertEqual(br.state, resilience.OPEN)
        with mock.patch(
            "subprocess.run",
            side_effect=AssertionError("open breaker must not pay the 90 s child"),
        ):
            caps, probe_ok = devices._probe_caps_subprocess()
        self.assertEqual(caps, {"complex": False, "fft": False})
        self.assertFalse(probe_ok)
        self.assertGreaterEqual(br.snapshot()["short_circuits"], 1)

        # half-open after the cooldown: the next probe really runs and closes it
        clock.t += 301.0
        good = _pytypes.SimpleNamespace(returncode=0, stdout="CAPS 1 1\n", stderr="")
        with mock.patch("subprocess.run", return_value=good):
            caps, probe_ok = devices._probe_caps_subprocess()
        self.assertEqual(caps, {"complex": True, "fft": True})
        self.assertTrue(probe_ok)
        self.assertEqual(br.state, resilience.CLOSED)

    def test_injected_caps_fault_counts_as_relay_failure(self):
        br = resilience.breaker("backend.relay", failure_threshold=2, cooldown_s=300.0)
        resilience.arm_fault_plan(
            [{"site": "probe.caps", "on_call": 1, "kind": "backend-down"}]
        )
        with mock.patch(
            "subprocess.run", side_effect=AssertionError("injected fault must short-circuit")
        ):
            caps, probe_ok = devices._probe_caps_subprocess()
        self.assertEqual(caps, {"complex": False, "fft": False})
        self.assertFalse(probe_ok)
        self.assertEqual(br.snapshot()["failures"], 1)


# ------------------------------------------------------- cross-instance breaker state
class TestCrossInstanceBreakerSharing(_ResilienceCase):
    def test_bootstrap_returns_package_instance_once_imported(self):
        # heat_tpu is imported in this process, so the standalone loader must
        # hand back the package module — one plan, one breaker registry
        import _diag_bootstrap

        res = _diag_bootstrap.load_resilience()
        self.assertIs(res, resilience)

    def test_driver_probe_failures_reach_the_package_breaker(self):
        """Driver order — standalone resilience loaded BEFORE the package (the
        bench.py shape): failures its probes record must be visible to
        devices.relay_breaker() after heat_tpu imports, so caps probes really
        short-circuit on a relay the driver already measured as down."""
        here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ)
        env.update(JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
        code = (
            "import sys; sys.path.insert(0, %r)\n"
            "import _diag_bootstrap\n"
            "res = _diag_bootstrap.load_resilience()\n"
            "assert 'heat_tpu' not in sys.modules\n"
            "res.breaker('backend.relay', failure_threshold=2, cooldown_s=300.0)"
            ".record_failure('driver probe down')\n"
            "import heat_tpu  # the package instance adopts the registry\n"
            "from heat_tpu.core import devices\n"
            "snap = devices.relay_breaker().snapshot()\n"
            "assert snap['failures'] == 1, snap\n"
            "print('SHARED_BREAKER_OK')\n"
        ) % (here,)
        proc = subprocess.run(
            [sys.executable, "-c", code], env=env, capture_output=True, text=True,
            timeout=300,
        )
        self.assertEqual(proc.returncode, 0, proc.stderr[-1000:])
        self.assertIn("SHARED_BREAKER_OK", proc.stdout)


# ------------------------------------------------------------------ HLO byte-parity
class TestHLOByteParity(_ResilienceCase):
    """Armed-but-idle (plan at sites that never fire) and disarmed builds must
    compile byte-identical HLO: the resilience layer exists strictly OUTSIDE
    traced program bodies."""

    @staticmethod
    def _chain_hlos():
        _executor.clear_executor_cache()
        np_x = np.arange(8, dtype=np.float32)
        np_y = np.full(8, 0.5, dtype=np.float32)
        x = ht.array(np_x, split=0)
        y = ht.array(np_y, split=0)
        (x + y).sum().parray
        with _executor._lock:
            entries = [
                e for e in _executor._programs.values()
                if e is not _executor.UNSUPPORTED and e.arg_specs is not None
            ]
        texts = {}
        for entry in entries:
            fn = jax.jit(
                entry._traced(),
                out_shardings=entry.out_shardings,
                keep_unused=entry.donate_index is not None,
            )
            texts[entry.label] = fn.lower(*entry.arg_specs).compile().as_text()
        return texts

    def test_hlo_byte_parity_armed_vs_disarmed(self):
        diagnostics.disable()
        baseline = self._chain_hlos()
        self.assertGreaterEqual(len(baseline), 2, list(baseline))
        resilience.arm_fault_plan(
            [{"site": "never.fires", "on_call": 10**9, "kind": "raise"}]
        )
        armed = self._chain_hlos()
        self.assertEqual(armed, baseline, "arming a fault plan changed compiled HLO")
        resilience.disarm_fault_plan()
        again = self._chain_hlos()
        self.assertEqual(again, baseline, "disarming did not restore byte-identical HLO")


# ------------------------------------------------------------------ canned env plan (CI)
class TestEnvCannedPlan(_ResilienceCase):
    def test_env_canned_plan_end_to_end(self):
        """The CI chaos job's shape: a hermetic child arms a canned
        HEAT_TPU_FAULT_PLAN from the environment, computes through the faulted
        sites, and must match numpy bit-for-bit."""
        here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        plan = [
            {"site": "comm.shard", "on_call": 1, "kind": "raise"},
            {"site": "executor.execute", "on_call": 1, "count": 99, "kind": "raise"},
        ]
        ndev = os.environ.get("HEAT_TPU_TEST_DEVICES", "8")
        env = dict(os.environ)
        env.update(
            JAX_PLATFORMS="cpu",
            PALLAS_AXON_POOL_IPS="",
            XLA_FLAGS=f"--xla_force_host_platform_device_count={ndev}",
            HEAT_TPU_FAULT_PLAN=json.dumps(plan),
            HEAT_TPU_JIT_THRESHOLD="1",
        )
        code = (
            "import sys; sys.path.insert(0, %r)\n"
            "import numpy as np\n"
            "import heat_tpu as ht\n"
            "from heat_tpu.core import resilience\n"
            "assert resilience._armed, 'env plan must arm at import'\n"
            "np_a = np.arange(10, dtype=np.float32)\n"
            "x = ht.array(np_a, split=0)\n"
            "y = (x + 1.0) * 2.0\n"
            "np.testing.assert_array_equal(y.numpy(), (np_a + 1.0) * 2.0)\n"
            "stats = ht.executor_stats()\n"
            "assert stats['eager_fallbacks'] >= 1, stats\n"
            "print('CANNED_PLAN_OK')\n"
        ) % (here,)
        proc = subprocess.run(
            [sys.executable, "-c", code], env=env, capture_output=True, text=True,
            timeout=300,
        )
        self.assertEqual(proc.returncode, 0, proc.stderr[-1000:])
        self.assertIn("CANNED_PLAN_OK", proc.stdout)

    def test_env_canned_plan_deadline_exceeded_kind(self):
        """ISSUE 10 chaos shape: an env-armed plan fires `deadline-exceeded`
        inside a dispatch — the reader gets the TYPED error (no eager replay,
        no quarantine) and the very next force retries clean."""
        here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        plan = [
            {"site": "executor.execute", "on_call": 2, "count": 1,
             "kind": "deadline-exceeded"},
        ]
        ndev = os.environ.get("HEAT_TPU_TEST_DEVICES", "8")
        env = dict(os.environ)
        env.update(
            JAX_PLATFORMS="cpu",
            PALLAS_AXON_POOL_IPS="",
            XLA_FLAGS=f"--xla_force_host_platform_device_count={ndev}",
            HEAT_TPU_FAULT_PLAN=json.dumps(plan),
            HEAT_TPU_JIT_THRESHOLD="1",
        )
        code = (
            "import sys; sys.path.insert(0, %r)\n"
            "import numpy as np\n"
            "import heat_tpu as ht\n"
            "from heat_tpu.core import resilience\n"
            "assert resilience._armed, 'env plan must arm at import'\n"
            "np_a = np.arange(10, dtype=np.float32)\n"
            "y = (ht.array(np_a, split=0) + 1.0) * 2.0\n"
            "np.testing.assert_array_equal(y.numpy(), (np_a + 1.0) * 2.0)\n"
            "z = (ht.array(np_a * 2, split=0) + 1.0) * 2.0\n"
            "try:\n"
            "    z.numpy()\n"
            "    raise SystemExit('fault did not surface')\n"
            "except resilience.DeadlineExceeded:\n"
            "    pass\n"
            "np.testing.assert_array_equal(z.numpy(), (np_a * 2 + 1.0) * 2.0)\n"
            "stats = ht.executor_stats()\n"
            "assert stats['expired_requests'] >= 1, stats\n"
            "assert stats['eager_fallbacks'] == 0, stats\n"
            "assert not stats['quarantined'], stats\n"
            "print('DEADLINE_PLAN_OK')\n"
        ) % (here,)
        proc = subprocess.run(
            [sys.executable, "-c", code], env=env, capture_output=True, text=True,
            timeout=300,
        )
        self.assertEqual(proc.returncode, 0, proc.stderr[-1000:])
        self.assertIn("DEADLINE_PLAN_OK", proc.stdout)


if __name__ == "__main__":
    import unittest

    unittest.main()
