"""Cross-request result cache tests (ISSUE 17 tentpole).

Covers the memoization tier's correctness contract in-process:

- **default-off parity** — with ``HEAT_TPU_RESULT_CACHE`` unset the tier is
  disabled, holds no shards, and records nothing under traffic;
- **store/hit round trip** — a repeated fused force over generation-registered
  leaves stores once and then hits, bit-identical values;
- **post-clear recompute** — ``ht.clear_executor_cache()`` drops every entry
  and the first post-clear read of any key is a guaranteed recompute
  (satellite: the documented clear contract);
- **donation-epoch invalidation is exact** — donating one registered buffer
  invalidates exactly the entries that alias it, neighbours keep hitting;
- **generation-bump invalidation** — re-registering a tag at a higher
  generation makes entries keyed on the old generation fail validation
  closed (the ``StagedBatch``/``restage`` contract);
- **swap hammer vs cache-off bit-parity** — the same request sequence
  interleaved with ``swap_state`` swaps produces IDENTICAL values with the
  cache on and off, and a threaded hammer never observes a torn or stale
  value;
- **poisoned entry** — a corrupted entry is a typed ``cache-corrupt``
  rejection on the always-on resilience stream and a correct recompute,
  never a served value;
- **uncacheable bypass** — RNG-labelled programs and unregistered operands
  never consult or fill.
"""

import itertools
import os
import shutil
import tempfile
import threading

import numpy as np

import heat_tpu as ht
from heat_tpu.core import _executor, _result_cache, diagnostics
from heat_tpu.testing import TestCase

_OLD = {}

N = 1024

# The generation table is MONOTONIC by contract (``max(prev, gen)``) and
# survives ``clear()`` — identity metadata, not cache contents — so each test
# case registers under its own tag family, exactly like production callers
# draw ids from one process-wide counter (``workloads._GEN_COUNTER``).
_TAG_SEQ = itertools.count()


def setUpModule():
    # compile-on-first-miss so the first dispatch already has a program spec
    # (the program half of the cache key); conftest's threshold-2 would make
    # every first call eager and shift the store to the second call
    for knob, val in (("HEAT_TPU_JIT_THRESHOLD", "1"),):
        _OLD[knob] = os.environ.get(knob)
        os.environ[knob] = val
    _executor.reload_env_knobs()


def tearDownModule():
    for knob, old in _OLD.items():
        if old is None:
            os.environ.pop(knob, None)
        else:
            os.environ[knob] = old
    _executor.reload_env_knobs()


def _cache_corrupt_events():
    with diagnostics._lock:
        return [
            e for e in diagnostics._resilience_events
            if e.get("kind") == "cache-corrupt"
            and e.get("site") == "executor.result_cache"
        ]


class _CacheCase(TestCase):
    """Arms the tier, registers two staged leaves, restores everything."""

    def setUp(self):
        super().setUp()
        _executor.clear_executor_cache()
        old = os.environ.get("HEAT_TPU_RESULT_CACHE")

        def restore():
            if old is None:
                os.environ.pop("HEAT_TPU_RESULT_CACHE", None)
            else:
                os.environ["HEAT_TPU_RESULT_CACHE"] = old
            _executor.clear_executor_cache()  # also re-reads the knob

        os.environ["HEAT_TPU_RESULT_CACHE"] = "1"
        _executor.reload_env_knobs()
        self.addCleanup(restore)
        self.tag = f"t{next(_TAG_SEQ)}"
        self.a = ht.array(np.arange(N, dtype=np.float32), split=0)
        self.b = ht.array(np.full(N, 2.0, np.float32), split=0)
        _result_cache.register_generation(self.a.parray, f"{self.tag}:a", 1)
        _result_cache.register_generation(self.b.parray, f"{self.tag}:b", 1)

    def _force(self, x, y):
        out = x * y + y
        return out.numpy()

    def _rc(self):
        return ht.executor_stats()["result_cache"]


class TestDefaultOff(TestCase):
    def test_off_by_default_and_records_nothing(self):
        _executor.clear_executor_cache()  # re-reads the (unset) knob
        self.assertFalse(_result_cache.enabled())
        rc = ht.executor_stats()["result_cache"]
        self.assertFalse(rc["enabled"])
        self.assertEqual(rc["shards"], 0)
        a = ht.array(np.arange(64, dtype=np.float32), split=0)
        _result_cache.register_generation(a.parray, "off:a", 1)
        for _ in range(3):
            (a + 1.0).numpy()
        rc = ht.executor_stats()["result_cache"]
        self.assertEqual(
            (rc["hits"], rc["misses"], rc["stores"], rc["entries"]),
            (0, 0, 0, 0),
        )
        # the fold-out aliases ride executor_stats unconditionally
        stats = ht.executor_stats()
        for k in ("cache_hits", "cache_misses", "cache_bytes_saved",
                  "cache_invalidations"):
            self.assertEqual(stats[k], 0)


class TestStoreHit(_CacheCase):
    def test_repeat_is_store_then_hits_bit_identical(self):
        first = self._force(self.a, self.b)
        rc0 = self._rc()
        self.assertGreaterEqual(rc0["stores"], 1)
        again = self._force(self.a, self.b)
        rc1 = self._rc()
        self.assertGreater(rc1["hits"], rc0["hits"])
        self.assertEqual(rc1["stores"], rc0["stores"])
        self.assertGreater(rc1["bytes_saved"], 0)
        self.assertEqual(first.tobytes(), again.tobytes())

    def test_clear_executor_cache_guarantees_recompute(self):
        self._force(self.a, self.b)
        self._force(self.a, self.b)
        self.assertGreaterEqual(self._rc()["entries"], 1)
        ht.clear_executor_cache()
        rc = self._rc()
        self.assertEqual(rc["entries"], 0)
        self.assertEqual(rc["bytes"], 0)
        # the first post-clear read recomputes (a fresh store, not a hit)
        value = self._force(self.a, self.b)
        rc = self._rc()
        self.assertEqual(rc["hits"], 0)
        self.assertGreaterEqual(rc["stores"], 1)
        expect = np.arange(N, dtype=np.float32) * 2.0 + 2.0
        self.assertEqual(value.tobytes(), expect.tobytes())


class TestInvalidation(_CacheCase):
    def test_donation_invalidates_exactly_the_aliasing_entries(self):
        self._force(self.a, self.b)            # entry keyed on (tag:a, tag:b)
        c = ht.array(np.full(N, 5.0, np.float32), split=0)
        _result_cache.register_generation(c.parray, f"{self.tag}:c", 1)
        (c + 1.0).numpy()                      # entry keyed on (t:c) only
        rc0 = self._rc()
        dropped = _result_cache.note_donation([id(self.a.parray)])
        self.assertEqual(dropped, 1)           # exact: only the a-entry dies
        self.assertEqual(self._rc()["invalidations"],
                         rc0["invalidations"] + 1)
        hits0 = self._rc()["hits"]
        (c + 1.0).numpy()                      # the c-entry still serves
        self.assertGreater(self._rc()["hits"], hits0)
        stores0 = self._rc()["stores"]
        self._force(self.a, self.b)            # the a-entry recomputes
        self.assertGreaterEqual(self._rc()["stores"], stores0)

    def test_generation_bump_fails_stale_entries_closed(self):
        first = self._force(self.a, self.b)
        self._force(self.a, self.b)
        self.assertGreaterEqual(self._rc()["hits"], 1)
        # the restage event: the SAME buffer re-registers at a higher
        # generation, so the old entry's (tag, gen) pairs no longer validate
        _result_cache.register_generation(self.a.parray, f"{self.tag}:a", 2)
        rc0 = self._rc()
        again = self._force(self.a, self.b)    # digests at gen 2: fresh key
        rc1 = self._rc()
        self.assertEqual(rc1["hits"], rc0["hits"])
        self.assertGreater(rc1["stores"], rc0["stores"])
        self.assertEqual(first.tobytes(), again.tobytes())
        # the stale gen-1 entry is swept (never serveable either way)
        self.assertGreaterEqual(
            _result_cache.invalidate_prefix(f"{self.tag}:a"), 1
        )


class TestPoisonedEntry(_CacheCase):
    def test_poisoned_entry_rejects_typed_and_recomputes(self):
        clean = self._force(self.a, self.b)
        self._force(self.a, self.b)
        ev0 = len(_cache_corrupt_events())
        self.assertGreaterEqual(_result_cache._poison_one(), 1)
        rc0 = self._rc()
        value = self._force(self.a, self.b)
        rc1 = self._rc()
        self.assertEqual(value.tobytes(), clean.tobytes())
        self.assertEqual(rc1["rejects"], rc0["rejects"] + 1)
        events = _cache_corrupt_events()
        self.assertEqual(len(events), ev0 + 1)
        self.assertIn("ResultCacheCorrupt", events[-1]["detail"])


class TestUncacheable(_CacheCase):
    def test_rng_labels_never_consult(self):
        for label in ("rand[2]", "defer:normal..add[3]", "dropout"):
            self.assertTrue(_result_cache.uncacheable_label(label))
        self.assertFalse(_result_cache.uncacheable_label("defer:mul..add[2]"))

    def test_unregistered_operand_is_uncacheable(self):
        big = ht.array(np.zeros((256, 256), np.float32), split=0)
        stores0 = self._rc()["stores"]
        for _ in range(2):
            (big + 1.0).numpy()
        self.assertEqual(self._rc()["stores"], stores0)
        self.assertIsNone(
            _result_cache.digest_args((big.parray,))
        )

    def test_scalar_and_registered_digests(self):
        d = _result_cache.digest_args((1.5, self.a.parray))
        self.assertEqual(d[0], ("s", "float", "1.5"))
        self.assertEqual(d[1], ("g", f"{self.tag}:a", 1))


class TestSwapHammer(TestCase):
    """``swap_state`` under the cache: bit-parity with cache-off, and a
    threaded hammer that must never observe a torn or stale value."""

    SCALES = {"a": 1.0, "b": 3.0}

    def setUp(self):
        super().setUp()
        self.tmp = tempfile.mkdtemp(prefix="ht-result-cache-swap-")
        self.addCleanup(shutil.rmtree, self.tmp, ignore_errors=True)
        self.gen = {}
        for name, scale in self.SCALES.items():
            w = ht.array(np.full(N, scale, np.float32), split=0)
            self.gen[name] = os.path.join(self.tmp, f"gen_{name}")
            ht.save_checkpoint({"w": w}, self.gen[name])
        old = os.environ.get("HEAT_TPU_RESULT_CACHE")

        def restore():
            if old is None:
                os.environ.pop("HEAT_TPU_RESULT_CACHE", None)
            else:
                os.environ["HEAT_TPU_RESULT_CACHE"] = old
            _executor.clear_executor_cache()
            sched = _executor._get_scheduler()
            sched.resume()
            sched.reopen()

        self.addCleanup(restore)

    def _arm(self, on: bool):
        os.environ["HEAT_TPU_RESULT_CACHE"] = "1" if on else "0"
        _executor.clear_executor_cache()

    def _sequence(self, pool, batches, swaps_at):
        """Serve a deterministic slot rotation, swapping generations at the
        given request indices; returns the value list."""
        values = []
        order = ["b", "a", "b"]
        for i in range(24):
            if i in swaps_at:
                ht.serving.swap_state(pool, self.gen[order[len(values) % 3]])
            x = batches[i % len(batches)]
            y = x * pool.state["w"] + pool.state["w"]
            values.append(float(np.asarray(y.parray)[0]))
        return values

    def _build(self, name):
        pool = ht.serving.ModelPool(
            {"w": ht.zeros((N,), split=0)}, name=name
        ).load(self.gen["a"])
        batches = []
        for s in range(4):
            v = ht.array(np.full(N, float(s + 1), np.float32), split=0)
            _result_cache.register_generation(v.parray, f"{name}:x:{s}", 1)
            batches.append(v)
        return pool, batches

    def test_swap_sequence_bit_parity_with_cache_off(self):
        swaps_at = {6, 13, 19}
        self._arm(False)
        pool, batches = self._build("hammer-off")
        baseline = self._sequence(pool, batches, swaps_at)
        self._arm(True)
        pool, batches = self._build("hammer-on")
        cached = self._sequence(pool, batches, swaps_at)
        self.assertEqual(baseline, cached)
        rc = ht.executor_stats()["result_cache"]
        self.assertGreater(rc["hits"], 0)          # the cache actually served
        self.assertGreater(rc["invalidations"], 0)  # the swaps actually swept

    def test_threaded_hammer_never_serves_stale_or_torn(self):
        self._arm(True)
        pool, batches = self._build("hammer-t")
        stop = threading.Event()
        bad = []
        valid = {s: {scale * (s + 2) for scale in self.SCALES.values()}
                 for s in range(len(batches))}

        from heat_tpu.core import resilience

        def worker(seed):
            i = seed
            while not stop.is_set():
                s = i % len(batches)
                i += 1
                try:
                    y = batches[s] * pool.state["w"] + pool.state["w"]
                    v = float(np.asarray(y.parray)[0])
                except (resilience.Shed, resilience.DeadlineExceeded,
                        resilience.RequestCancelled,
                        resilience.DrainTimeout):
                    continue  # typed lifecycle errors during quiesce are fine
                if v not in valid[s]:
                    bad.append((s, v))
                    return

        threads = [threading.Thread(target=worker, args=(k,), daemon=True)
                   for k in range(3)]
        for t in threads:
            t.start()
        try:
            for gen in ("b", "a", "b"):
                ht.serving.swap_state(pool, self.gen[gen],
                                      drain_timeout_s=30.0)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=30)
        self.assertEqual(bad, [])
        # post-quiesce: every request now sees the final generation only
        final = self.SCALES["b"]
        for s in range(len(batches)):
            y = batches[s] * pool.state["w"] + pool.state["w"]
            self.assertEqual(float(np.asarray(y.parray)[0]),
                             final * (s + 2))
