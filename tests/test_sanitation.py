"""Sanitation and stride-tricks tests (reference heat/core/tests/test_sanitation.py,
test_stride_tricks.py)."""

import numpy as np

import heat_tpu as ht
from heat_tpu.core import sanitation, stride_tricks
from heat_tpu.testing import TestCase


class TestSanitation(TestCase):
    def test_sanitize_in(self):
        sanitation.sanitize_in(ht.ones(3))
        with self.assertRaises(TypeError):
            sanitation.sanitize_in(np.ones(3))

    def test_sanitize_infinity(self):
        self.assertEqual(
            sanitation.sanitize_infinity(ht.arange(5, dtype=ht.int32)),
            np.iinfo(np.int32).max,
        )
        self.assertEqual(
            sanitation.sanitize_infinity(ht.ones(3, dtype=ht.float32)),
            float(np.finfo(np.float32).max),
        )

    def test_sanitize_out(self):
        out = ht.zeros((4,), split=0)
        sanitation.sanitize_out(out, (4,), 0, out.comm)
        with self.assertRaises(TypeError):
            sanitation.sanitize_out(np.zeros(4), (4,), 0, out.comm)
        with self.assertRaises(ValueError):
            sanitation.sanitize_out(out, (5,), 0, out.comm)

    def test_sanitize_distribution(self):
        a = ht.arange(8, split=0)
        b = ht.arange(8, split=None)
        b2 = sanitation.sanitize_distribution(b, target=a)
        self.assertEqual(b2.split, 0)
        np.testing.assert_array_equal(b2.numpy(), b.numpy())

    def test_scalar_to_1d(self):
        s = ht.array(5.0)
        v = sanitation.scalar_to_1d(s)
        self.assertEqual(v.gshape, (1,))

    def test_sanitize_sequence(self):
        self.assertEqual(sanitation.sanitize_sequence([1, 2]), [1, 2])
        self.assertEqual(sanitation.sanitize_sequence((1, 2)), [1, 2])


class TestStrideTricks(TestCase):
    def test_broadcast_shape(self):
        self.assertEqual(stride_tricks.broadcast_shape((5, 4), (4,)), (5, 4))
        self.assertEqual(stride_tricks.broadcast_shape((1, 3), (2, 1)), (2, 3))
        self.assertEqual(stride_tricks.broadcast_shapes((2, 1, 4), (3, 1), (1,)), (2, 3, 4))
        with self.assertRaises(ValueError):
            stride_tricks.broadcast_shape((3,), (4,))

    def test_sanitize_axis(self):
        self.assertEqual(stride_tricks.sanitize_axis((4, 5), -1), 1)
        self.assertEqual(stride_tricks.sanitize_axis((4, 5), None), None)
        self.assertEqual(stride_tricks.sanitize_axis((4, 5, 6), (0, -1)), (0, 2))
        with self.assertRaises(ValueError):
            stride_tricks.sanitize_axis((4, 5), 2)
        with self.assertRaises(TypeError):
            stride_tricks.sanitize_axis((4, 5), "x")

    def test_sanitize_shape(self):
        self.assertEqual(stride_tricks.sanitize_shape(5), (5,))
        self.assertEqual(stride_tricks.sanitize_shape((3, 4)), (3, 4))
        with self.assertRaises(ValueError):
            stride_tricks.sanitize_shape((-2, 3))
        with self.assertRaises((TypeError, ValueError)):
            stride_tricks.sanitize_shape("bad")

    def test_sanitize_slice(self):
        sl = stride_tricks.sanitize_slice(slice(None, None, None), 10)
        self.assertEqual((sl.start, sl.stop, sl.step), (0, 10, 1))
        sl = stride_tricks.sanitize_slice(slice(-3, None, None), 10)
        self.assertEqual(sl.start, 7)
        with self.assertRaises(TypeError):
            stride_tricks.sanitize_slice("nope", 10)


if __name__ == "__main__":
    import unittest

    unittest.main()
