"""Serving benchmark harness tests (ISSUE 7): the in-process load generator,
the BENCH-record shape the CI gate consumes, and the lower-envelope gate
logic itself (including the no-baseline-entry visible warning).

The full four-workload suite runs in the dedicated CI ``serving`` job
(``benchmarks/serving/harness.py --smoke --check``); here one cheap workload
exercises the whole pipeline so tier-1 keeps the harness honest without
paying the full load run.
"""

import json
import os
import sys

import numpy as np

from heat_tpu.core import profiler
from heat_tpu.testing import TestCase

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.serving import harness  # noqa: E402
from benchmarks.serving.workloads import BUILDERS, build_workloads  # noqa: E402


class TestServingHarness(TestCase):
    def tearDown(self):
        profiler.disable()
        profiler.reset()
        super().tearDown()

    def test_closed_and_open_records(self):
        collected = []
        records, failed = harness.run(
            smoke=True,
            requests=6,
            concurrency=2,
            which=["sparse_matvec"],
            emit=lambda line: collected.append(json.loads(line)),
        )
        self.assertFalse(failed)  # no baseline given: nothing can fail
        self.assertEqual([r["mode"] for r in records], ["closed", "open"])
        closed, open_ = records
        self.assertEqual(closed["metric"], "serving_sparse_matvec_closed_rps")
        self.assertEqual(closed["requests"], 6)
        self.assertGreater(closed["value"], 0)
        self.assertLessEqual(closed["p50_ms"], closed["p99_ms"])
        self.assertLessEqual(closed["p99_ms"], closed["max_ms"])
        # the profiler histogram snapshot rides along and agrees on the count
        self.assertEqual(closed["latency_hist"]["count"], 6)
        self.assertEqual(closed["profiler_schema"], profiler.SCHEMA)
        self.assertIn("offered_rps", open_)
        self.assertEqual(open_["latency_hist"]["count"], open_["requests"])
        # histogram p50 and the exact nearest-rank p50 describe the same data
        # (log-bucket resolution plus open-loop queueing skew — loose bound)
        h50 = closed["latency_hist"]["p50_s"] * 1e3
        self.assertLess(abs(h50 - closed["p50_ms"]) / closed["p50_ms"], 0.25)
        self.assertEqual(len(collected), 2)
        # ISSUE 10: every record carries the scheduler-pressure block so
        # overload behaviour is visible in the bench trajectory
        for rec in records:
            sched = rec["scheduler"]
            for key in ("queue_full_events", "queue_depth_peak",
                        "queued_dispatches", "shed", "expired", "cancelled"):
                self.assertIn(key, sched)
            # a plain (deadline-free) run never sheds or cancels anything
            self.assertEqual(sched["shed"], 0)
            self.assertEqual(sched["cancelled"], 0)

    def test_trace_and_diag_artifacts(self):
        import tempfile

        d = tempfile.mkdtemp(prefix="ht_serving_")
        self.addCleanup(lambda: __import__("shutil").rmtree(d, ignore_errors=True))
        trace = os.path.join(d, "trace.json")
        diag = os.path.join(d, "diag.json")
        harness.run(
            smoke=True, requests=4, concurrency=2, which=["sparse_matvec"],
            trace_out=trace, diag_out=diag, emit=lambda line: None,
        )
        with open(trace) as f:
            obj = json.load(f)
        self.assertEqual(obj["schema"], profiler.TRACE_SCHEMA)
        self.assertTrue(any(e.get("ph") == "B" for e in obj["traceEvents"]))
        with open(diag) as f:
            rep = json.load(f)
        self.assertIn("profiler", rep)
        self.assertIn(
            "request.sparse_matvec.closed", rep["profiler"]["histograms"]
        )

    def test_gate_logic(self):
        rec = {
            "workload": "wl", "devices": 8, "value": 100.0,
            "p50_ms": 10.0, "p99_ms": 20.0,
        }
        out = []
        emit = lambda line: out.append(json.loads(line))  # noqa: E731
        # healthy vs a loose envelope: no failure, no output
        self.assertFalse(harness._gate_closed(
            rec, {"min_rps": 50, "max_p50_ms": 40, "max_p99_ms": 80}, emit))
        self.assertEqual(out, [])
        # throughput collapse
        self.assertTrue(harness._gate_closed(rec, {"min_rps": 200}, emit))
        self.assertIn("below the baseline", out[-1]["error"])
        # p99 blowout
        self.assertTrue(harness._gate_closed(rec, {"max_p99_ms": 5}, emit))
        self.assertIn("p99_ms", out[-1]["error"])
        # no baseline entry: a VISIBLE warning, not a silent pass
        self.assertFalse(harness._gate_closed(rec, None, emit))
        self.assertIn("not gated", out[-1]["warning"])

    def test_gate_failure_returned_not_raised(self):
        # an impossible envelope: the in-process caller gets failed=True as a
        # VALUE (the CLI, not run(), owns the non-zero exit)
        out = []
        records, failed = harness.run(
            smoke=True, requests=4, concurrency=2, which=["sparse_matvec"],
            check=True,
            baseline={str(self.world_size): {
                "sparse_matvec": {"min_rps": 1e12}
            }},
            emit=lambda line: out.append(json.loads(line)),
        )
        self.assertTrue(failed)
        self.assertTrue(any("error" in rec for rec in out))
        self.assertEqual(len(records), 2)

    def test_baseline_covers_ci_matrix(self):
        path = os.path.join(
            os.path.dirname(os.path.abspath(harness.__file__)),
            "serving_baseline.json",
        )
        with open(path) as f:
            baseline = json.load(f)
        for devices in ("3", "8"):
            self.assertIn(devices, baseline)
            for name in BUILDERS:
                envelope = baseline[devices].get(name)
                self.assertIsNotNone(
                    envelope, f"no envelope for {name} at {devices} devices"
                )
                self.assertGreater(envelope["min_rps"], 0)
                self.assertGreater(envelope["max_p99_ms"],
                                   envelope["max_p50_ms"])

    def test_workloads_are_buildable_and_reentrant(self):
        # the cheap workloads build and serve two sequential requests with
        # bit-identical setup state (read-only after build)
        for wl in build_workloads(smoke=True, which=["cdist_knn"]):
            wl.fn(0)
            wl.fn(1)

    def test_percentile_nearest_rank(self):
        lats = [i / 1000.0 for i in range(1, 101)]  # 1..100 ms
        self.assertAlmostEqual(harness._percentile_ms(lats, 0.50), 51.0)
        self.assertAlmostEqual(harness._percentile_ms(lats, 0.99), 99.0)
        self.assertAlmostEqual(harness._percentile_ms(lats, 1.0), 100.0)


class TestMixedScenario(TestCase):
    """ISSUE 8 satellite: all four request types through ONE shared pool."""

    def tearDown(self):
        profiler.disable()
        profiler.reset()
        super().tearDown()

    def test_mixed_records_and_per_workload_breakdown(self):
        records, failed = harness.run(
            smoke=True, requests=8, concurrency=2, which=["mixed"],
            emit=lambda line: None,
        )
        self.assertFalse(failed)
        self.assertEqual([r["workload"] for r in records], ["mixed", "mixed"])
        closed, open_ = records
        self.assertEqual(closed["metric"], "serving_mixed_closed_rps")
        # the mixed record's scheduler block carries the per-workload
        # lifecycle breakdown (all zero in a deadline-free run)
        self.assertIn("per_workload", closed["scheduler"])
        # the interleave rotates deterministically over all four types
        self.assertEqual(set(closed["per_workload"]), set(BUILDERS))
        self.assertEqual(
            sum(v["requests"] for v in closed["per_workload"].values()),
            closed["requests"],
        )
        # the aggregate histogram is the exact merge of the per-type ones
        self.assertEqual(closed["latency_hist"]["count"], closed["requests"])
        self.assertIn("offered_rps", open_)

    def test_open_rps_pinning(self):
        records, _ = harness.run(
            smoke=True, requests=6, concurrency=2, which=["sparse_matvec"],
            open_rps={"sparse_matvec": 123.0}, emit=lambda line: None,
        )
        open_ = [r for r in records if r["mode"] == "open"][0]
        self.assertEqual(open_["offered_rps"], 123.0)

    def test_mixed_baseline_covers_ci_matrix(self):
        path = os.path.join(
            os.path.dirname(os.path.abspath(harness.__file__)),
            "serving_baseline.json",
        )
        with open(path) as f:
            baseline = json.load(f)
        for devices in ("3", "8"):
            envelope = baseline[devices].get("mixed")
            self.assertIsNotNone(envelope,
                                 f"no mixed envelope at {devices} devices")
            self.assertGreater(envelope["min_rps"], 0)
        self.assertIn("_async_gate", baseline)
        recorded = baseline["_async_gate"]["recorded"]
        self.assertLessEqual(recorded["open_p99_geomean_ratio"], 1.0,
                             "the recorded async win must actually be a win")


class TestAsyncGateEvaluation(TestCase):
    """The async-executor serving gate's record math (pure, no load run)."""

    @staticmethod
    def _arm(name, closed_p50, open_p99, offered=100.0):
        return [
            {"workload": name, "mode": "closed", "value": 100.0,
             "p50_ms": closed_p50, "p99_ms": closed_p50 * 2},
            {"workload": name, "mode": "open", "value": 80.0,
             "p50_ms": closed_p50, "p99_ms": open_p99,
             "offered_rps": offered},
        ]

    def test_async_win_passes(self):
        from benchmarks.serving import async_gate

        ser = self._arm("wl", 10.0, 40.0)
        asy = self._arm("wl", 10.0, 30.0)
        comps, failed = async_gate.evaluate(ser, asy, emit=lambda s: None)
        self.assertFalse(failed)
        summary = [c for c in comps if c["metric"] == "serving_async_gate_summary"]
        self.assertEqual(len(summary), 1)
        self.assertLess(summary[0]["open_p99_geomean_ratio"], 1.0)

    def test_p99_regression_fails(self):
        from benchmarks.serving import async_gate

        ser = self._arm("wl", 10.0, 40.0)
        asy = self._arm("wl", 10.0, 44.0)  # 1.1x: worse overall
        _, failed = async_gate.evaluate(ser, asy, emit=lambda s: None)
        self.assertTrue(failed)

    def test_closed_p50_regression_fails(self):
        from benchmarks.serving import async_gate

        ser = self._arm("wl", 10.0, 40.0)
        asy = self._arm("wl", 10.0 * 1.5, 30.0)  # p99 wins but p50 blew up
        _, failed = async_gate.evaluate(ser, asy, emit=lambda s: None)
        self.assertTrue(failed)

    def test_missing_arm_warns_and_fails_empty(self):
        from benchmarks.serving import async_gate

        out = []
        _, failed = async_gate.evaluate(
            self._arm("wl", 10.0, 40.0), [],
            emit=lambda s: out.append(json.loads(s)),
        )
        self.assertTrue(failed)
        self.assertTrue(any("warning" in r or "error" in r for r in out))

class TestOverloadGateEvaluation(TestCase):
    """The overload gate's record math (ISSUE 10; pure, no load run)."""

    @staticmethod
    def _score(offered=100, admitted=None, shed=0, failed=0, ok=None,
               goodput=50.0, p99=100.0):
        if admitted is None:
            admitted = offered - shed - failed
        return {
            "offered": offered, "admitted": admitted, "shed": shed,
            "failed": failed, "outcomes": {},
            "accounted": admitted + shed + failed == offered,
            "goodput_rps": goodput, "admitted_p99_ms": p99,
            "shed_fraction": round(shed / offered, 4),
            "deadline_ms": 50.0, "wall_s": 1.0,
        }

    def _rec(self, base, shed):
        return [{"workload": "wl", "baseline": base, "shed": shed}]

    def test_shed_preserves_while_baseline_collapses_passes(self):
        from benchmarks.serving import overload_gate

        comps = self._rec(
            self._score(goodput=5.0, p99=1500.0),          # collapsed baseline
            self._score(shed=60, goodput=40.0, p99=80.0),  # preserved shed arm
        )
        env = {"wl": {"min_goodput_rps": 18, "max_admitted_p99_ms": 400}}
        self.assertFalse(overload_gate.evaluate(comps, env, emit=lambda s: None))

    def test_shed_arm_collapse_fails(self):
        from benchmarks.serving import overload_gate

        comps = self._rec(
            self._score(goodput=5.0, p99=1500.0),
            self._score(shed=60, goodput=2.0, p99=900.0),  # shedding broken
        )
        env = {"wl": {"min_goodput_rps": 18, "max_admitted_p99_ms": 400}}
        self.assertTrue(overload_gate.evaluate(comps, env, emit=lambda s: None))

    def test_baseline_meeting_envelope_fails_the_gate(self):
        from benchmarks.serving import overload_gate

        # the "overload" did not collapse the baseline: the gate proves
        # nothing and must say so
        comps = self._rec(
            self._score(goodput=40.0, p99=90.0),
            self._score(shed=10, goodput=45.0, p99=80.0),
        )
        env = {"wl": {"min_goodput_rps": 18, "max_admitted_p99_ms": 400}}
        self.assertTrue(overload_gate.evaluate(comps, env, emit=lambda s: None))

    def test_broken_accounting_fails(self):
        from benchmarks.serving import overload_gate

        bad = self._score(shed=60, goodput=40.0, p99=80.0)
        bad["admitted"] -= 1  # one request vanished untyped
        bad["accounted"] = False
        comps = self._rec(self._score(goodput=5.0, p99=1500.0), bad)
        env = {"wl": {"min_goodput_rps": 18, "max_admitted_p99_ms": 400}}
        out = []
        self.assertTrue(overload_gate.evaluate(
            comps, env, emit=lambda s: out.append(json.loads(s))))
        self.assertTrue(any("accounting" in r.get("error", "") for r in out))

    def test_missing_envelope_warns_visibly(self):
        from benchmarks.serving import overload_gate

        comps = self._rec(
            self._score(goodput=5.0, p99=1500.0),
            self._score(shed=60, goodput=40.0, p99=80.0),
        )
        out = []
        # envelopes dict exists but has no entry for this workload -> warning
        # plus a gate failure (nothing was actually gated)
        self.assertTrue(overload_gate.evaluate(
            comps, {}, emit=lambda s: out.append(json.loads(s))))
        self.assertTrue(any("warning" in r for r in out))

    def test_overload_baseline_covers_ci_matrix(self):
        path = os.path.join(
            os.path.dirname(os.path.abspath(harness.__file__)),
            "serving_baseline.json",
        )
        with open(path) as f:
            baseline = json.load(f)
        self.assertIn("_overload_gate", baseline)
        envelopes = baseline["_overload_gate"]["envelopes"]
        from benchmarks.serving import overload_gate

        zoo = [name for name, _ in overload_gate.build_overload_workloads()]
        for devices in ("3", "8"):
            self.assertIn(devices, envelopes)
            for name in zoo:
                env = envelopes[devices].get(name)
                self.assertIsNotNone(
                    env, f"no overload envelope for {name} at {devices} devices"
                )
                self.assertGreater(env["min_goodput_rps"], 0)
                self.assertGreater(env["max_admitted_p99_ms"], 0)
