"""``ht.serving`` zero-downtime hot-swap tests (ISSUE 13 leg 4): staged
load+verify, drain → rebind → reopen through the scheduler's ``quiesce``,
typed ``SwapFailed`` rollback, and the ledger/flight trail. The swap-UNDER-LOAD
accounting gate (admitted + shed + failed == offered across the boundary)
lives in ``benchmarks/serving/swap_gate.py``; these are the correctness and
failure-path units."""

import glob
import os
import shutil
import tempfile
import threading

import numpy as np

import heat_tpu as ht
from heat_tpu.core import _executor, checkpoint as _ckpt
from heat_tpu.core import diagnostics, resilience, telemetry
from heat_tpu.testing import TestCase

N = 1024


class TestSwap(TestCase):
    def setUp(self):
        self.tmp = tempfile.mkdtemp()
        resilience.disarm_fault_plan()
        resilience.reset(clear_breakers=True)
        self.gen = {}
        for name, scale in (("a", 1.0), ("b", 3.0)):
            w = ht.array(np.full(N, scale, np.float32), split=0)
            self.gen[name] = os.path.join(self.tmp, f"gen_{name}")
            ht.save_checkpoint({"w": w}, self.gen[name])
        self.pool = ht.serving.ModelPool(
            {"w": ht.zeros((N,), split=0)}, name="t"
        ).load(self.gen["a"])
        self.x = ht.array(np.arange(N, dtype=np.float32), split=0)
        self.base = float(np.arange(N, dtype=np.float32).sum())

    def tearDown(self):
        shutil.rmtree(self.tmp, ignore_errors=True)
        resilience.disarm_fault_plan()
        resilience.reset(clear_breakers=True)
        sched = _executor._get_scheduler()
        sched.resume()
        sched.reopen()

    def _serve(self) -> float:
        return float((self.x * self.pool.state["w"]).sum().item())

    def test_swap_changes_served_generation(self):
        self.assertEqual(self._serve(), self.base)
        entry = ht.serving.swap_state(self.pool, self.gen["b"])
        self.assertTrue(entry["ok"])
        self.assertEqual(self.pool.generation, self.gen["b"])
        self.assertEqual(self._serve(), 3.0 * self.base)
        # admission is open again: the scheduler serves normally
        self.assertFalse(_executor._get_scheduler().draining())
        ledger = self.pool.swap_ledger()
        self.assertEqual([e["ok"] for e in ledger], [True])
        self.assertGreaterEqual(entry["total_s"], entry["drain_s"])

    def test_corrupt_new_generation_rolls_back_typed(self):
        bad = os.path.join(self.tmp, "gen_bad")
        ht.save_checkpoint({"w": ht.array(np.full(N, 9.0, np.float32), split=0)}, bad)
        chunk = sorted(glob.glob(os.path.join(bad, "leaf_0.c*.bin")))[0]
        with open(chunk, "r+b") as fh:
            fh.truncate(4)
        with self.assertRaises(resilience.SwapFailed) as ctx:
            ht.serving.swap_state(self.pool, bad)
        self.assertEqual(ctx.exception.stage, "stage")
        # serving continues on the old generation, admission open
        self.assertEqual(self.pool.generation, self.gen["a"])
        self.assertEqual(self._serve(), self.base)
        self.assertFalse(_executor._get_scheduler().draining())
        # the rollback left its trail: ledger, resilience event, flight ring
        ledger = self.pool.swap_ledger()
        self.assertEqual(ledger[-1]["ok"], False)
        self.assertEqual(ledger[-1]["stage"], "stage")
        events = [
            e for e in diagnostics.report()["resilience_events"]
            if e["site"] == "serving.swap" and e["kind"] == "swap-failed"
        ]
        self.assertTrue(events)
        flights = [
            e for e in telemetry.flight_events()
            if e["site"] == "serving.swap"
        ]
        self.assertTrue(flights)

    def test_drain_timeout_rolls_back_and_reopens(self):
        sched = _executor._get_scheduler()
        sched.pause()  # hold queued work so the drain cannot flush... except
        # drain() lifts pause; park a fake in-flight execution instead
        with sched._shards[0]._cv:
            sched._shards[0]._active += 1
        try:
            with self.assertRaises(resilience.SwapFailed) as ctx:
                ht.serving.swap_state(
                    self.pool, self.gen["b"], drain_timeout_s=0.2
                )
        finally:
            with sched._shards[0]._cv:
                sched._shards[0]._active -= 1
                sched._shards[0]._cv.notify_all()
        self.assertEqual(ctx.exception.stage, "drain")
        self.assertEqual(self.pool.generation, self.gen["a"])
        self.assertFalse(sched.draining(), "quiesce must reopen after timeout")
        self.assertEqual(self._serve(), self.base)

    def test_requests_during_swap_are_never_dropped(self):
        """Requests racing the swap window all complete (old or new values,
        never garbage, never an untyped error)."""
        results, errors = [], []
        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                try:
                    results.append(self._serve())
                except Exception as exc:  # any failure fails the test below
                    errors.append(exc)
                    return

        threads = [threading.Thread(target=hammer, daemon=True) for _ in range(4)]
        for t in threads:
            t.start()
        try:
            ht.serving.swap_state(self.pool, self.gen["b"])
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=30)
        self.assertEqual(errors, [])
        valid = {self.base, 3.0 * self.base}
        self.assertTrue(set(results) <= valid, set(results) - valid)
        self.assertEqual(self._serve(), 3.0 * self.base)

    def test_quiesce_tolerate_shed_runs_body_closed_then_reraises(self):
        """``tolerate_shed=True``: a timed-out drain (everything already
        shed typed) must still run the critical section INSIDE the closed
        window — the peer-failover sentinel clear depends on it — and the
        DrainTimeout re-raises on exit for the caller's accounting."""
        sched = _executor._get_scheduler()
        with sched._shards[0]._cv:
            sched._shards[0]._active += 1  # park a fake in-flight execution
        ran = []
        try:
            with self.assertRaises(resilience.DrainTimeout):
                with sched.quiesce(0.2, tolerate_shed=True):
                    ran.append(sched.draining())
        finally:
            with sched._shards[0]._cv:
                sched._shards[0]._active -= 1
                sched._shards[0]._cv.notify_all()
        self.assertEqual(ran, [True], "body must run while still closed")
        self.assertFalse(sched.draining(), "quiesce must reopen after exit")
        # default behaviour unchanged: the body is skipped on a timeout
        with sched._shards[0]._cv:
            sched._shards[0]._active += 1
        try:
            with self.assertRaises(resilience.DrainTimeout):
                with sched.quiesce(0.2):
                    self.fail("body must not run on an intolerant timeout")
        finally:
            with sched._shards[0]._cv:
                sched._shards[0]._active -= 1
                sched._shards[0]._cv.notify_all()
        self.assertFalse(sched.draining())

    def test_on_peer_failure_drain_timeout_clears_sentinel_before_reopen(self):
        """The failover ordering contract: even when the drain times out,
        the abort sentinel is cleared while admission is STILL closed, so
        no request admitted after reopen can be shed on the stale abort."""
        from heat_tpu.core import supervision

        sched = _executor._get_scheduler()
        supervision.arm(supervision.LocalCoordinator(), rank=0, nprocs=2,
                        start_thread=False)
        try:
            supervision.post_abort("peer-failed", rank=1, last_seen_s=1.0)
            observed = []
            orig_reopen_check = sched.draining

            def spying_reset(_real=supervision.reset_abort):
                observed.append(("reset", orig_reopen_check()))
                _real()

            with sched._shards[0]._cv:
                sched._shards[0]._active += 1  # the drain cannot flush: DrainTimeout
            real_reset = supervision.reset_abort
            supervision.reset_abort = spying_reset
            try:
                entry = self.pool.on_peer_failure(
                    resilience.PeerFailed(1, 1.0, detected_by=0),
                    drain_timeout_s=0.2, scheduler=sched,
                )
            finally:
                supervision.reset_abort = real_reset
                with sched._shards[0]._cv:
                    sched._shards[0]._active -= 1
                    sched._shards[0]._cv.notify_all()
            self.assertEqual(observed, [("reset", True)],
                             "sentinel must clear while still draining")
            self.assertTrue(entry["ok"])
            self.assertIsNone(supervision.aborted())
            self.assertFalse(sched.draining())
            self.assertEqual(self._serve(), self.base)  # pool serves on
        finally:
            supervision.disarm()
            supervision.reset_abort()

    def test_quiesce_reopens_on_body_failure(self):
        sched = _executor._get_scheduler()
        with self.assertRaises(RuntimeError):
            with sched.quiesce(5.0):
                self.assertTrue(sched.draining())
                raise RuntimeError("rebind exploded")
        self.assertFalse(sched.draining())

    def test_quiesce_yields_to_concurrent_shutdown_drain(self):
        """A drain that runs DURING the quiesce window (the atexit shutdown
        drain racing a swap) closed admission on purpose: quiesce must not
        reopen it — admitted work would queue into a shutting-down loop."""
        sched = _executor._get_scheduler()
        with sched.quiesce(5.0):
            sched.drain(5.0)  # the shutdown drain wins the race
        self.assertTrue(sched.draining(), "quiesce reopened a shutdown drain")
        sched.reopen()
        self.assertFalse(sched.draining())

    def test_quiesce_respects_pre_existing_drain(self):
        """quiesce entered while admission is already closed leaves it
        closed on exit — the earlier drain's owner decides when to reopen."""
        sched = _executor._get_scheduler()
        sched.drain(5.0)
        try:
            with sched.quiesce(5.0):
                pass
            self.assertTrue(sched.draining())
        finally:
            sched.reopen()
        self.assertFalse(sched.draining())


if __name__ == "__main__":
    import unittest

    unittest.main()
