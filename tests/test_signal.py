"""Signal tests (reference heat/core/tests/test_signal.py)."""

import numpy as np

import heat_tpu as ht
from heat_tpu.testing import TestCase


class TestConvolve(TestCase):
    def test_convolve_modes(self):
        sig = np.ones(10, dtype=np.float32)
        ker = np.arange(3, dtype=np.float32)
        for split_a in (None, 0):
            for split_v in (None, 0):
                a = ht.array(sig, split=split_a)
                v = ht.array(ker, split=split_v)
                for mode in ("full", "same", "valid"):
                    self.assert_array_equal(
                        ht.convolve(a, v, mode=mode), np.convolve(sig, ker, mode=mode)
                    )

    def test_convolve_random(self):
        rng = np.random.default_rng(0)
        sig = rng.random(23)
        ker = rng.random(5)
        a, v = ht.array(sig, split=0), ht.array(ker)
        for mode in ("full", "same", "valid"):
            self.assert_array_equal(ht.convolve(a, v, mode=mode), np.convolve(sig, ker, mode=mode))

    def test_swap_and_errors(self):
        # kernel longer than signal swaps (numpy does the same)
        sig, ker = np.ones(3), np.arange(7.0)
        self.assert_array_equal(ht.convolve(ht.array(sig), ht.array(ker)), np.convolve(sig, ker))
        with self.assertRaises(ValueError):
            ht.convolve(ht.ones((3, 3)), ht.ones(3))
        with self.assertRaises(ValueError):
            ht.convolve(ht.ones(10), ht.ones(4), mode="same")
        with self.assertRaises(ValueError):
            ht.convolve(ht.ones(10), ht.ones(3), mode="bogus")

    def test_overlap_add_path(self):
        """The shard_map halo/overlap-add schedule agrees with numpy for ragged
        lengths, large-vs-chunk kernels (fallback), and every mode."""
        rng = np.random.default_rng(7)
        for n in (self.world_size * 8, self.world_size * 8 + 3, 65):
            sig = rng.random(n).astype(np.float32)
            for m in (2, 5, 9):
                ker = rng.random(m).astype(np.float32)
                a, v = ht.array(sig, split=0), ht.array(ker)
                for mode in ("full", "valid") + (("same",) if m % 2 else ()):
                    got = ht.convolve(a, v, mode=mode)
                    expected = np.convolve(sig, ker, mode=mode)
                    self.assertEqual(got.gshape, expected.shape)
                    np.testing.assert_allclose(
                        got.numpy(), expected, rtol=1e-5,
                        err_msg=f"n={n} m={m} mode={mode}",
                    )

    def test_int_promotion(self):
        a = np.arange(8)
        v = np.array([1, 2, 1])
        r = ht.convolve(ht.array(a, split=0), ht.array(v))
        self.assert_array_equal(r, np.convolve(a, v))


if __name__ == "__main__":
    import unittest

    unittest.main()
