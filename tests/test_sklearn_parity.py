"""Estimator parity vs scikit-learn (the reference models its estimator API and
semantics on sklearn; these tests pin the numerics to the canonical implementation
across every split)."""

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.testing import TestCase

sklearn = pytest.importorskip("sklearn")


def _blobs(n=120, d=5, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(0, 5, (classes, d)).astype(np.float32)
    y = rng.integers(0, classes, n)
    x = centers[y] + rng.normal(0, 0.8, (n, d)).astype(np.float32)
    return x.astype(np.float32), y.astype(np.int64)


class TestKNNParity(TestCase):
    def test_predictions_match(self):
        from sklearn.neighbors import KNeighborsClassifier as SkKNN

        x, y = _blobs()
        xt, yt = x[:90], y[:90]
        xq = x[90:]
        sk = SkKNN(n_neighbors=5).fit(xt, yt)
        expected = sk.predict(xq)
        for split in (None, 0):
            knn = ht.classification.kneighborsclassifier.KNeighborsClassifier(n_neighbors=5)
            knn.fit(ht.array(xt, split=split), ht.array(yt, split=split))
            got = knn.predict(ht.array(xq, split=split)).numpy().ravel()
            # well-separated blobs: identical labels
            np.testing.assert_array_equal(got, expected)


class TestGaussianNBParity(TestCase):
    def test_statistics_and_predictions(self):
        from sklearn.naive_bayes import GaussianNB as SkNB

        x, y = _blobs(seed=1)
        sk = SkNB().fit(x, y)
        for split in (None, 0):
            nb = ht.naive_bayes.GaussianNB()
            nb.fit(ht.array(x, split=split), ht.array(y, split=split))
            np.testing.assert_allclose(np.asarray(nb.theta_), sk.theta_, rtol=1e-4)
            np.testing.assert_allclose(np.asarray(nb.var_), sk.var_, rtol=1e-3, atol=1e-5)
            np.testing.assert_array_equal(
                nb.predict(ht.array(x, split=split)).numpy().ravel(), sk.predict(x)
            )

    def test_partial_fit_parity(self):
        from sklearn.naive_bayes import GaussianNB as SkNB

        x, y = _blobs(seed=2)
        classes = np.unique(y)
        sk = SkNB()
        sk.partial_fit(x[:60], y[:60], classes=classes)
        sk.partial_fit(x[60:], y[60:])
        nb = ht.naive_bayes.GaussianNB()
        nb.partial_fit(ht.array(x[:60], split=0), ht.array(y[:60], split=0), classes=ht.array(classes))
        nb.partial_fit(ht.array(x[60:], split=0), ht.array(y[60:], split=0))
        np.testing.assert_allclose(np.asarray(nb.theta_), sk.theta_, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(nb.var_), sk.var_, rtol=1e-3, atol=1e-5)


class TestScalerParity(TestCase):
    def setUp(self):
        rng = np.random.default_rng(3)
        self.x = (rng.random((40, 6)) * 100 - 50).astype(np.float32)

    def _check(self, ht_cls, sk_obj, **kw):
        from numpy.testing import assert_allclose

        expected = sk_obj.fit_transform(self.x)
        for split in (None, 0):
            scaler = ht_cls(**kw)
            hx = ht.array(self.x, split=split)
            got = scaler.fit_transform(hx)
            assert_allclose(got.numpy(), expected, rtol=1e-4, atol=1e-4,
                            err_msg=f"{ht_cls.__name__} split={split}")
            # inverse round-trip
            back = scaler.inverse_transform(got)
            assert_allclose(back.numpy(), self.x, rtol=1e-3, atol=1e-3)

    def test_standard(self):
        from sklearn.preprocessing import StandardScaler

        self._check(ht.preprocessing.StandardScaler, StandardScaler())

    def test_minmax(self):
        from sklearn.preprocessing import MinMaxScaler

        self._check(ht.preprocessing.MinMaxScaler, MinMaxScaler())

    def test_maxabs(self):
        from sklearn.preprocessing import MaxAbsScaler

        self._check(ht.preprocessing.MaxAbsScaler, MaxAbsScaler())

    def test_robust(self):
        from sklearn.preprocessing import RobustScaler

        self._check(ht.preprocessing.RobustScaler, RobustScaler())

    def test_normalizer(self):
        from sklearn.preprocessing import Normalizer

        expected = Normalizer().fit_transform(self.x)
        for split in (None, 0):
            got = ht.preprocessing.Normalizer().fit_transform(ht.array(self.x, split=split))
            np.testing.assert_allclose(got.numpy(), expected, rtol=1e-4)


class TestKMeansParity(TestCase):
    def test_inertia_comparable(self):
        from sklearn.cluster import KMeans as SkKMeans

        x, _ = _blobs(n=300, d=4, classes=4, seed=4)
        sk = SkKMeans(n_clusters=4, n_init=5, random_state=0, max_iter=100).fit(x)
        km = ht.cluster.KMeans(n_clusters=4, init="kmeans++", max_iter=100, random_state=0)
        km.fit(ht.array(x, split=0))
        # same data, both converged: inertia within 5% of sklearn's multi-init best
        self.assertLessEqual(km.inertia_, sk.inertia_ * 1.05)

    def test_lasso_vs_sklearn_shrinkage(self):
        from sklearn.linear_model import Lasso as SkLasso

        rng = np.random.default_rng(5)
        n, d = 100, 8
        X = rng.standard_normal((n, d)).astype(np.float64)
        w = np.zeros(d)
        w[:3] = (3.0, -2.0, 1.5)
        yv = X @ w + 0.01 * rng.standard_normal(n)
        lam = 0.1
        # sklearn minimizes (1/2n)||y-Xw||² + α||w||₁; the coordinate-descent form
        # here uses per-coordinate soft thresholding by lam on the correlation —
        # match by scaling
        sk = SkLasso(alpha=lam / n * np.sum(X[:, 0] ** 2) / 2, fit_intercept=True)
        sk.fit(X, yv)
        Xi = np.hstack([np.ones((n, 1)), X])
        est = ht.regression.lasso.Lasso(lam=lam, max_iter=500, tol=1e-8)
        est.fit(ht.array(Xi, split=0), ht.array(yv, split=0))
        got = est.coef_.numpy().ravel()
        # support recovery: the three true features dominate
        self.assertEqual(set(np.argsort(-np.abs(got))[:3]), {0, 1, 2})


if __name__ == "__main__":
    import unittest

    unittest.main()
