"""Sparse tests (reference heat/sparse/tests/)."""

import numpy as np

import heat_tpu as ht
from heat_tpu.testing import TestCase


def _sample(seed=0, shape=(8, 6), density=0.3):
    rng = np.random.default_rng(seed)
    dense = rng.random(shape) * (rng.random(shape) < density)
    return dense.astype(np.float32)


class TestSparse(TestCase):
    def test_factory_from_dense(self):
        dense = _sample()
        for split in (None, 0):
            s = ht.sparse.sparse_csr_matrix(ht.array(dense, split=split), split=split)
            self.assertEqual(s.shape, dense.shape)
            self.assertEqual(s.split, split)
            self.assertEqual(s.nnz, int((dense != 0).sum()))
            np.testing.assert_allclose(s.numpy(), dense, rtol=1e-6)

    def test_csr_views(self):
        dense = _sample(1)
        s = ht.sparse.sparse_csr_matrix(ht.array(dense), split=0)
        try:
            from scipy import sparse as sp

            ref = sp.csr_matrix(dense)
            np.testing.assert_array_equal(np.asarray(s.indptr), ref.indptr)
            np.testing.assert_array_equal(np.asarray(s.indices), ref.indices)
            np.testing.assert_allclose(np.asarray(s.data), ref.data, rtol=1e-6)
        except ImportError:
            indptr = np.asarray(s.indptr)
            self.assertEqual(indptr[0], 0)
            self.assertEqual(indptr[-1], s.nnz)
        # local views cover a prefix of rows
        lptr = np.asarray(s.lindptr)
        self.assertEqual(lptr[0], 0)
        self.assertEqual(len(np.asarray(s.ldata)), lptr[-1])
        self.assertEqual(s.lshape[1], dense.shape[1])

    def test_dcsr_attribute_surface(self):
        """Reference test_dcsrmatrix.py attribute names (data/indices/indptr/nnz/
        shape/dtype/larray/astype) across splits."""
        dense = _sample(7)
        for split in (None, 0):
            s = ht.sparse.sparse_csr_matrix(ht.array(dense, split=split))
            self.assertEqual(s.shape, dense.shape)
            self.assertEqual(int(s.nnz), int(np.count_nonzero(dense)))
            self.assertEqual(int(s.gnnz), int(s.nnz))
            self.assertIs(s.dtype, ht.float32)
            self.assertEqual(len(np.asarray(s.indptr)), dense.shape[0] + 1)
            self.assertEqual(len(np.asarray(s.indices)), int(s.nnz))
            self.assertEqual(len(np.asarray(s.data)), int(s.nnz))
            self.assertIsNotNone(s.larray)
            d = s.astype(ht.float64)
            self.assertIs(d.dtype, ht.float64)
            np.testing.assert_allclose(
                np.asarray(d.todense().numpy()), dense, rtol=1e-6
            )

    def test_add_mul_sparse(self):
        a, b = _sample(2), _sample(3)
        sa = ht.sparse.sparse_csr_matrix(ht.array(a), split=0)
        sb = ht.sparse.sparse_csr_matrix(ht.array(b), split=0)
        np.testing.assert_allclose((sa + sb).numpy(), a + b, rtol=1e-6)
        np.testing.assert_allclose(ht.sparse.mul(sa, sb).numpy(), a * b, rtol=1e-6)

    def test_scalar_ops(self):
        a = _sample(4)
        sa = ht.sparse.sparse_csr_matrix(ht.array(a))
        # scalar ops act on stored values (torch/scipy CSR semantics)
        prod = ht.sparse.mul(sa, 2.0)
        np.testing.assert_allclose(prod.numpy(), a * 2.0, rtol=1e-6)
        self.assertEqual(prod.nnz, sa.nnz)

    def test_to_dense_to_sparse(self):
        a = _sample(5)
        x = ht.array(a, split=0)
        s = ht.sparse.to_sparse(x)
        self.assertEqual(s.split, 0)
        d = ht.sparse.to_dense(s)
        self.assertEqual(d.split, 0)
        np.testing.assert_allclose(d.numpy(), a, rtol=1e-6)
        self.assert_array_equal(s.todense(), a)

    def test_union_keeps_explicit_zeros(self):
        """Sparse−sparse results keep the union pattern without pruning explicit
        zeros (torch/scipy CSR semantics; the reference never drops result zeros)."""
        a = np.array([[1.0, 0.0], [2.0, 3.0]], np.float32)
        b = np.array([[-1.0, 5.0], [0.0, -3.0]], np.float32)
        sa = ht.sparse.sparse_csr_matrix(ht.array(a), split=0)
        sb = ht.sparse.sparse_csr_matrix(ht.array(b), split=0)
        s = ht.sparse.add(sa, sb)
        # values cancel at (0,0) and (1,1) but the union pattern keeps 4 stored
        # slots — torch.sparse semantics (the reference's backend); scipy's `+`
        # would prune the cancelled entries
        np.testing.assert_allclose(s.numpy(), a + b, rtol=1e-6)
        self.assertEqual(s.nnz, 4)

    def test_large_random_vs_scipy(self):
        try:
            from scipy import sparse as sp
        except ImportError:
            self.skipTest("scipy not available")
        a, b = _sample(8, (50, 40), 0.1), _sample(9, (50, 40), 0.1)
        sa = ht.sparse.sparse_csr_matrix(ht.array(a), split=0)
        sb = ht.sparse.sparse_csr_matrix(ht.array(b), split=0)
        for ht_fn, sp_res in (
            (ht.sparse.add, sp.csr_matrix(a) + sp.csr_matrix(b)),
            (ht.sparse.mul, sp.csr_matrix(a).multiply(sp.csr_matrix(b)).tocsr()),
        ):
            got = ht_fn(sa, sb)
            np.testing.assert_allclose(got.numpy(), sp_res.toarray(), rtol=1e-6)

    def test_ragged_rows_split(self):
        """Row counts that do not divide the mesh still produce correct CSR views."""
        a = _sample(10, (self.world_size * 2 + 1, 5), 0.4)
        s = ht.sparse.sparse_csr_matrix(ht.array(a, split=0), split=0)
        np.testing.assert_allclose(s.numpy(), a, rtol=1e-6)
        self.assertEqual(s.nnz, int((a != 0).sum()))
        indptr = np.asarray(s.indptr)
        self.assertEqual(len(indptr), a.shape[0] + 1)
        self.assertEqual(indptr[-1], s.nnz)

    def test_round_trip_preserves_dtype_and_shape(self):
        for dt in (ht.float32, ht.float64):
            a = _sample(11).astype(np.dtype(dt.jax_type()))
            s = ht.sparse.sparse_csr_matrix(ht.array(a, split=0), split=0)
            self.assertIs(s.dtype, dt)
            back = ht.sparse.to_dense(s)
            self.assertIs(back.dtype, dt)
            np.testing.assert_allclose(back.numpy(), a, rtol=1e-6)

    def test_counts_displs_nnz(self):
        a = _sample(12, (self.world_size * 2, 5), 0.4)
        s = ht.sparse.sparse_csr_matrix(ht.array(a, split=0), split=0)
        self.assertEqual(s.is_distributed(), self.world_size > 1)
        counts, displs = s.counts_displs_nnz()
        self.assertEqual(sum(counts), s.nnz)
        self.assertEqual(displs[0], 0)
        for i in range(1, len(displs)):
            self.assertEqual(displs[i], displs[i - 1] + counts[i - 1])
        with self.assertRaises(ValueError):
            ht.sparse.sparse_csr_matrix(ht.array(a)).counts_displs_nnz()

    def test_astype_and_errors(self):
        a = _sample(6)
        s = ht.sparse.sparse_csr_matrix(ht.array(a))
        d = s.astype(ht.float64)
        self.assertEqual(d.dtype, ht.float64)
        with self.assertRaises(ValueError):
            ht.sparse.sparse_csr_matrix(ht.array(a), split=1)
        with self.assertRaises(ValueError):
            b = ht.sparse.sparse_csr_matrix(ht.array(_sample(7, shape=(4, 4))))
            ht.sparse.add(s, b)
        with self.assertRaises(TypeError):
            ht.sparse.add(np.zeros((2, 2)), s)


if __name__ == "__main__":
    import unittest

    unittest.main()
