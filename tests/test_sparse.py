"""Sparse tests (reference heat/sparse/tests/)."""

import numpy as np

import heat_tpu as ht
from heat_tpu.testing import TestCase


def _sample(seed=0, shape=(8, 6), density=0.3):
    rng = np.random.default_rng(seed)
    dense = rng.random(shape) * (rng.random(shape) < density)
    return dense.astype(np.float32)


class TestSparse(TestCase):
    def test_factory_from_dense(self):
        dense = _sample()
        for split in (None, 0):
            s = ht.sparse.sparse_csr_matrix(ht.array(dense, split=split), split=split)
            self.assertEqual(s.shape, dense.shape)
            self.assertEqual(s.split, split)
            self.assertEqual(s.nnz, int((dense != 0).sum()))
            np.testing.assert_allclose(s.numpy(), dense, rtol=1e-6)

    def test_csr_views(self):
        dense = _sample(1)
        s = ht.sparse.sparse_csr_matrix(ht.array(dense), split=0)
        try:
            from scipy import sparse as sp

            ref = sp.csr_matrix(dense)
            np.testing.assert_array_equal(np.asarray(s.indptr), ref.indptr)
            np.testing.assert_array_equal(np.asarray(s.indices), ref.indices)
            np.testing.assert_allclose(np.asarray(s.data), ref.data, rtol=1e-6)
        except ImportError:
            indptr = np.asarray(s.indptr)
            self.assertEqual(indptr[0], 0)
            self.assertEqual(indptr[-1], s.nnz)
        # local views cover a prefix of rows
        lptr = np.asarray(s.lindptr)
        self.assertEqual(lptr[0], 0)
        self.assertEqual(len(np.asarray(s.ldata)), lptr[-1])
        self.assertEqual(s.lshape[1], dense.shape[1])

    def test_add_mul_sparse(self):
        a, b = _sample(2), _sample(3)
        sa = ht.sparse.sparse_csr_matrix(ht.array(a), split=0)
        sb = ht.sparse.sparse_csr_matrix(ht.array(b), split=0)
        np.testing.assert_allclose((sa + sb).numpy(), a + b, rtol=1e-6)
        np.testing.assert_allclose(ht.sparse.mul(sa, sb).numpy(), a * b, rtol=1e-6)

    def test_scalar_ops(self):
        a = _sample(4)
        sa = ht.sparse.sparse_csr_matrix(ht.array(a))
        # scalar ops act on stored values (torch/scipy CSR semantics)
        prod = ht.sparse.mul(sa, 2.0)
        np.testing.assert_allclose(prod.numpy(), a * 2.0, rtol=1e-6)
        self.assertEqual(prod.nnz, sa.nnz)

    def test_to_dense_to_sparse(self):
        a = _sample(5)
        x = ht.array(a, split=0)
        s = ht.sparse.to_sparse(x)
        self.assertEqual(s.split, 0)
        d = ht.sparse.to_dense(s)
        self.assertEqual(d.split, 0)
        np.testing.assert_allclose(d.numpy(), a, rtol=1e-6)
        self.assert_array_equal(s.todense(), a)

    def test_astype_and_errors(self):
        a = _sample(6)
        s = ht.sparse.sparse_csr_matrix(ht.array(a))
        d = s.astype(ht.float64)
        self.assertEqual(d.dtype, ht.float64)
        with self.assertRaises(ValueError):
            ht.sparse.sparse_csr_matrix(ht.array(a), split=1)
        with self.assertRaises(ValueError):
            b = ht.sparse.sparse_csr_matrix(ht.array(_sample(7, shape=(4, 4))))
            ht.sparse.add(s, b)
        with self.assertRaises(TypeError):
            ht.sparse.add(np.zeros((2, 2)), s)


if __name__ == "__main__":
    import unittest

    unittest.main()
