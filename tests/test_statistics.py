"""Statistics tests (reference heat/core/tests/test_statistics.py): every assertion runs
for every split axis via the assert_func_equal split sweep."""

import numpy as np

import heat_tpu as ht
from heat_tpu.testing import TestCase


class TestArgReductions(TestCase):
    def test_argmax(self):
        self.assert_func_equal((7, 5), ht.argmax, np.argmax, distributed_result=False)
        self.assert_func_equal(
            (7, 5), ht.argmax, np.argmax, heat_args={"axis": 0}, numpy_args={"axis": 0}
        )
        self.assert_func_equal(
            (4, 6, 3), ht.argmax, np.argmax, heat_args={"axis": 1}, numpy_args={"axis": 1}
        )
        self.assert_func_equal(
            (4, 6, 3), ht.argmax, np.argmax, heat_args={"axis": -1}, numpy_args={"axis": -1}
        )

    def test_argmin(self):
        self.assert_func_equal((7, 5), ht.argmin, np.argmin, distributed_result=False)
        self.assert_func_equal(
            (7, 5), ht.argmin, np.argmin, heat_args={"axis": 1}, numpy_args={"axis": 1}
        )

    def test_argmax_split_preserved(self):
        x = ht.array(np.arange(24).reshape(4, 6), split=0)
        r = ht.argmax(x, axis=1)
        self.assertEqual(r.split, 0)
        r = ht.argmax(x, axis=0)
        self.assertEqual(r.split, None)

    def test_argmax_keepdims(self):
        a = np.random.default_rng(0).random((3, 5))
        x = ht.array(a, split=1)
        self.assert_array_equal(ht.argmax(x, axis=0, keepdims=True), np.argmax(a, axis=0, keepdims=True))


class TestMoments(TestCase):
    def test_mean(self):
        self.assert_func_equal((8, 6), ht.mean, np.mean, data_types=(np.float32, np.float64))
        self.assert_func_equal(
            (8, 6), ht.mean, np.mean, heat_args={"axis": 0}, numpy_args={"axis": 0},
            data_types=(np.float64,),
        )
        self.assert_func_equal(
            (4, 5, 6), ht.mean, np.mean, heat_args={"axis": 2}, numpy_args={"axis": 2},
            data_types=(np.float64,),
        )

    def test_var_std(self):
        self.assert_func_equal((9, 4), ht.var, np.var, data_types=(np.float64,))
        self.assert_func_equal(
            (9, 4), ht.var, np.var, heat_args={"axis": 0, "ddof": 1},
            numpy_args={"axis": 0, "ddof": 1}, data_types=(np.float64,),
        )
        self.assert_func_equal((9, 4), ht.std, np.std, data_types=(np.float64,))
        self.assert_func_equal(
            (9, 4), ht.std, np.std, heat_args={"axis": 1}, numpy_args={"axis": 1},
            data_types=(np.float64,),
        )

    def test_max_min(self):
        self.assert_func_equal((7, 8), ht.max, np.max, distributed_result=False)
        self.assert_func_equal(
            (7, 8), ht.max, np.max, heat_args={"axis": 0}, numpy_args={"axis": 0}
        )
        self.assert_func_equal((7, 8), ht.min, np.min, distributed_result=False)
        self.assert_func_equal(
            (7, 8), ht.min, np.min, heat_args={"axis": 1}, numpy_args={"axis": 1}
        )

    def test_maximum_minimum(self):
        rng = np.random.default_rng(3)
        a, b = rng.random((6, 5)), rng.random((6, 5))
        for split in (None, 0, 1):
            x, y = ht.array(a, split=split), ht.array(b, split=split)
            self.assert_array_equal(ht.maximum(x, y), np.maximum(a, b))
            self.assert_array_equal(ht.minimum(x, y), np.minimum(a, b))

    def test_average(self):
        rng = np.random.default_rng(4)
        a = rng.random((5, 7))
        w = rng.random(7)
        for split in (None, 0, 1):
            x = ht.array(a, split=split)
            self.assert_array_equal(ht.average(x), np.average(a))
            self.assert_array_equal(
                ht.average(x, axis=1, weights=ht.array(w)), np.average(a, axis=1, weights=w)
            )
        r, s = ht.average(ht.array(a, split=0), axis=0, returned=True)
        e, t = np.average(a, axis=0, returned=True)
        self.assert_array_equal(r, e)
        np.testing.assert_allclose(s.numpy(), t)

    def test_skew_kurtosis(self):
        rng = np.random.default_rng(5)
        a = rng.standard_normal((40,))
        try:
            from scipy import stats  # noqa
            has_scipy = True
        except ImportError:
            has_scipy = False
        x = ht.array(a, split=0)
        # against manual formulas
        n = a.size
        m = a.mean()
        m2 = ((a - m) ** 2).mean()
        m3 = ((a - m) ** 3).mean()
        g1 = m3 / m2**1.5 * np.sqrt(n * (n - 1)) / (n - 2)
        np.testing.assert_allclose(float(ht.skew(x).item()), g1, rtol=1e-5)
        m4 = ((a - m) ** 4).mean()
        g2 = m4 / m2**2
        k = ((n - 1) / ((n - 2) * (n - 3))) * ((n + 1) * g2 - 3 * (n - 1)) + 3 - 3
        np.testing.assert_allclose(float(ht.kurtosis(x).item()), k, rtol=1e-5)


class TestQuantiles(TestCase):
    def test_median(self):
        self.assert_func_equal((9,), ht.median, np.median, data_types=(np.float64,))
        self.assert_func_equal((6, 8), ht.median, np.median, data_types=(np.float64,))
        self.assert_func_equal(
            (6, 8), ht.median, np.median, heat_args={"axis": 0}, numpy_args={"axis": 0},
            data_types=(np.float64,),
        )

    def test_percentile(self):
        rng = np.random.default_rng(6)
        a = rng.random((10, 6))
        for split in (None, 0, 1):
            x = ht.array(a, split=split)
            self.assert_array_equal(ht.percentile(x, 30.0), np.percentile(a, 30.0))
            self.assert_array_equal(
                ht.percentile(x, 75.0, axis=0), np.percentile(a, 75.0, axis=0)
            )
            self.assert_array_equal(
                ht.percentile(x, [25.0, 50.0, 75.0], axis=1),
                np.percentile(a, [25.0, 50.0, 75.0], axis=1),
            )


class TestHistograms(TestCase):
    def test_bincount(self):
        a = np.array([0, 1, 1, 3, 2, 1, 7])
        for split in (None, 0):
            x = ht.array(a, split=split)
            self.assert_array_equal(ht.bincount(x), np.bincount(a))
            self.assert_array_equal(ht.bincount(x, minlength=10), np.bincount(a, minlength=10))

    def test_histc_histogram(self):
        rng = np.random.default_rng(7)
        a = rng.random(50).astype(np.float32)
        x = ht.array(a, split=0)
        h = ht.histc(x, bins=10)
        expected, _ = np.histogram(a, bins=10, range=(a.min(), a.max()))
        np.testing.assert_array_equal(h.numpy().astype(np.int64), expected)
        hh, edges = ht.histogram(x, bins=8)
        eh, ee = np.histogram(a, bins=8)
        np.testing.assert_array_equal(hh.numpy(), eh)
        np.testing.assert_allclose(edges.numpy(), ee, rtol=1e-6)

    def test_digitize_bucketize(self):
        a = np.array([0.2, 6.4, 3.0, 1.6, -1.0])
        bins = np.array([0.0, 1.0, 2.5, 4.0, 10.0])
        for split in (None, 0):
            x = ht.array(a, split=split)
            self.assert_array_equal(ht.digitize(x, ht.array(bins)), np.digitize(a, bins))
            self.assert_array_equal(
                ht.digitize(x, ht.array(bins), right=True), np.digitize(a, bins, right=True)
            )
            got = ht.bucketize(x, ht.array(bins))
            np.testing.assert_array_equal(got.numpy(), np.searchsorted(bins, a, side="left"))


class TestCov(TestCase):
    def test_cov(self):
        rng = np.random.default_rng(8)
        a = rng.random((4, 20))
        for split in (None, 0, 1):
            x = ht.array(a, split=split)
            self.assert_array_equal(ht.cov(x), np.cov(a))
            self.assert_array_equal(ht.cov(x, bias=True), np.cov(a, bias=True))
        b = rng.random((4, 20))
        x, y = ht.array(a, split=0), ht.array(b, split=0)
        self.assert_array_equal(ht.cov(x, y), np.cov(a, b))
        v = rng.random(30)
        self.assert_array_equal(ht.cov(ht.array(v, split=0)), np.cov(v))


class TestMethodAliases(TestCase):
    def test_methods(self):
        a = np.random.default_rng(9).random((6, 4))
        x = ht.array(a, split=0)
        self.assert_array_equal(x.mean(axis=0), a.mean(axis=0))
        self.assert_array_equal(x.var(axis=1), a.var(axis=1))
        self.assert_array_equal(x.std(), np.asarray(a.std()))
        self.assert_array_equal(x.max(axis=0), a.max(axis=0))
        self.assert_array_equal(x.min(axis=1), a.min(axis=1))
        self.assert_array_equal(x.argmax(axis=0), np.argmax(a, axis=0))
        self.assert_array_equal(x.median(axis=0), np.median(a, axis=0))


if __name__ == "__main__":
    import unittest

    unittest.main()
