"""Statistics edge matrix (VERDICT r4 #7): the reference test names missing from
tests/test_statistics.py (`/root/reference/heat/core/tests/test_statistics.py`,
1,432 LoC), driven across splits — including ragged extents, which now ride the
padded-physical reduce paths — against numpy/scipy oracles."""

import unittest

import numpy as np
import scipy.stats
import torch

import heat_tpu as ht
from heat_tpu.testing import TestCase as _BaseTestCase


class TestCase(_BaseTestCase):
    """Suite base (comm + per-shard-aware asserts) plus the local data helper."""

    def data(self, shape=(5, 13), seed=0):
        return np.random.default_rng(seed).standard_normal(shape).astype(np.float32)


class TestMinMaxFamily(TestCase):
    def test_max(self):
        a = self.data()
        for split in (None, 0, 1):
            x = ht.array(a, split=split)
            for axis in (None, 0, 1, (0, 1)):
                for keepdims in (False, True):
                    np.testing.assert_allclose(
                        ht.max(x, axis=axis, keepdims=keepdims).numpy(),
                        np.max(a, axis=axis, keepdims=keepdims),
                        err_msg=f"split={split} axis={axis} keepdims={keepdims}",
                    )
        out = ht.zeros(5, dtype=ht.float32)
        ht.max(ht.array(a, split=1), axis=1, out=out)
        np.testing.assert_allclose(out.numpy(), a.max(axis=1))

    def test_min(self):
        a = self.data(seed=1)
        for split in (None, 0, 1):
            x = ht.array(a, split=split)
            for axis in (None, 0, 1):
                np.testing.assert_allclose(
                    ht.min(x, axis=axis).numpy(), np.min(a, axis=axis)
                )

    def test_maximum(self):
        a, b = self.data(seed=2), self.data(seed=3)
        for split in (None, 0, 1):
            z = ht.maximum(ht.array(a, split=split), ht.array(b, split=split))
            np.testing.assert_allclose(z.numpy(), np.maximum(a, b))
        # NaN propagates elementwise; broadcasting row
        an = a.copy()
        an[0, 0] = np.nan
        z = ht.maximum(ht.array(an, split=0), ht.array(b[0]))
        np.testing.assert_allclose(z.numpy(), np.maximum(an, b[0]))

    def test_minimum(self):
        a, b = self.data(seed=4), self.data(seed=5)
        z = ht.minimum(ht.array(a, split=1), 0.25)
        np.testing.assert_allclose(z.numpy(), np.minimum(a, 0.25))
        z = ht.minimum(ht.array(a, split=0), ht.array(b, split=0))
        np.testing.assert_allclose(z.numpy(), np.minimum(a, b))


class TestMoments(TestCase):
    def test_std(self):
        P = self.comm.size
        a = self.data((3, 4 * P + 1), seed=6)  # ragged second dim
        for split in (None, 0, 1):
            x = ht.array(a, split=split)
            for axis in (None, 0, 1):
                for ddof in (0, 1):
                    np.testing.assert_allclose(
                        ht.std(x, axis=axis, ddof=ddof).numpy(),
                        a.std(axis=axis, ddof=ddof),
                        rtol=2e-4,
                        err_msg=f"split={split} axis={axis} ddof={ddof}",
                    )

    def test_var(self):
        P = self.comm.size
        a = self.data((4 * P + 3,), seed=7)
        for split in (None, 0):
            x = ht.array(a, split=split)
            for ddof in (0, 1):
                np.testing.assert_allclose(
                    ht.var(x, ddof=ddof).numpy(), a.var(ddof=ddof), rtol=2e-4
                )

    def test_skew(self):
        a = self.data((64,), seed=8)
        for split in (None, 0):
            got = float(ht.skew(ht.array(a, split=split)).numpy())
            want = float(scipy.stats.skew(a, bias=False))
            np.testing.assert_allclose(got, want, rtol=1e-3)
        m = self.data((6, 32), seed=9)
        got = ht.skew(ht.array(m, split=1), axis=1, unbiased=False).numpy()
        want = scipy.stats.skew(m, axis=1, bias=True)
        np.testing.assert_allclose(got, want, rtol=1e-3)

    def test_kurtosis(self):
        a = self.data((64,), seed=10)
        for split in (None, 0):
            got = float(ht.kurtosis(ht.array(a, split=split)).numpy())
            want = float(scipy.stats.kurtosis(a, bias=False))
            np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)
        m = self.data((6, 32), seed=11)
        got = ht.kurtosis(ht.array(m, split=0), axis=0, unbiased=False).numpy()
        want = scipy.stats.kurtosis(m, axis=0, bias=True)
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


class TestBinning(TestCase):
    def test_bucketize(self):
        boundaries = np.array([0.1, 0.5, 1.2, 3.0], np.float32)
        v = np.array([-1.0, 0.1, 0.4, 0.5, 2.9, 3.0, 4.0], np.float32)
        for split in (None, 0):
            for right in (False, True):
                got = ht.bucketize(ht.array(v, split=split), ht.array(boundaries), right=right)
                want = torch.bucketize(torch.tensor(v), torch.tensor(boundaries), right=right)
                np.testing.assert_array_equal(got.numpy(), want.numpy(),
                                              err_msg=f"right={right}")

    def test_digitize(self):
        bins = np.array([0.0, 1.0, 2.5, 4.0], np.float32)
        v = np.array([-0.5, 0.0, 0.9, 1.0, 2.5, 3.9, 4.0, 5.0], np.float32)
        for split in (None, 0):
            for right in (False, True):
                got = ht.digitize(ht.array(v, split=split), ht.array(bins), right=right)
                want = np.digitize(v, bins, right=right)
                np.testing.assert_array_equal(got.numpy(), want,
                                              err_msg=f"right={right}")

    def test_histc(self):
        v = self.data((257,), seed=12) * 3
        for split in (None, 0):
            got = ht.histc(ht.array(v, split=split), bins=16, min=-3.0, max=3.0)
            want = torch.histc(torch.tensor(v), bins=16, min=-3.0, max=3.0)
            np.testing.assert_allclose(got.numpy(), want.numpy())


if __name__ == "__main__":
    unittest.main()
