"""Tests for ``ht.supervision`` — the distributed supervision plane (ISSUE 14).

Single-process coverage of the machinery the real kill-a-rank proof
(tests/test_multiprocess.py::test_multiprocess_supervision +
tests/_mp_supervision_worker.py) exercises across processes: the heartbeat
state machine driven by an injected clock over a :class:`LocalCoordinator`,
watchdog fire/disarm, sentinel poll ordering at every chokepoint, the
supervised coordination waits' typed timeouts, the deterministic ``peer-dead``
fault kind, ``run_supervised``'s restart budget, the serving pool's failover
accounting, and the HLO byte-parity proof that an armed-but-idle plane never
touches a compiled program.
"""

import glob
import json
import os
import tempfile
import threading
import time
import unittest

import numpy as np

import heat_tpu as ht
import jax
from heat_tpu.core import _executor, checkpoint, diagnostics, resilience, supervision


class _SupervisionCase(unittest.TestCase):
    """Every test leaves the plane disarmed, abort-free, and knob-default."""

    def setUp(self):
        self._env = dict(os.environ)
        supervision.disarm()
        supervision.reset_abort()
        resilience.disarm_fault_plan()
        resilience.reset(clear_breakers=True)

    def tearDown(self):
        supervision.disarm()
        supervision.reset_abort()
        resilience.disarm_fault_plan()
        resilience.reset(clear_breakers=True)
        for key in set(os.environ) - set(self._env):
            del os.environ[key]
        os.environ.update(self._env)
        supervision.reload_env_knobs()
        _executor.reload_env_knobs()


class TestHeartbeatStateMachine(_SupervisionCase):
    """The monitor with an injected clock: detection is a pure function of
    observed beat changes on the observer's clock."""

    def _armed_pair(self, timeout=5.0):
        co = supervision.LocalCoordinator()
        clock = [0.0]
        mon = supervision.arm(co, rank=0, nprocs=2, peer_timeout_s=timeout,
                              clock=lambda: clock[0], start_thread=False)
        return co, clock, mon

    def test_silent_peer_past_budget_posts_typed_abort(self):
        co, clock, mon = self._armed_pair()
        mon.step(0.0)
        self.assertIsNone(supervision.aborted())
        clock[0] = 4.9
        mon.step(4.9)  # inside budget: no abort
        self.assertIsNone(supervision.aborted())
        clock[0] = 5.1
        mon.step(5.1)
        payload = supervision.aborted()
        self.assertIsNotNone(payload)
        self.assertEqual(payload["kind"], "peer-failed")
        self.assertEqual(payload["rank"], 1)
        self.assertGreater(payload["last_seen_s"], 5.0)
        with self.assertRaises(resilience.PeerFailed) as ctx:
            supervision.poll("test.site")
        self.assertEqual(ctx.exception.rank, 1)
        self.assertEqual(ctx.exception.detected_by, 0)

    def test_beating_peer_never_aborts(self):
        co, clock, mon = self._armed_pair()
        for t in (0.0, 4.0, 8.0, 12.0):
            co.set("heat_tpu/sup/%d/hb/1" % mon.generation, f"beat-{t}", True)
            clock[0] = t
            mon.step(t)
        self.assertIsNone(supervision.aborted())

    def test_stalled_beat_value_is_silence(self):
        # a peer whose beat value stops ADVANCING is as dead as one whose key
        # vanishes — liveness is change, not presence
        co, clock, mon = self._armed_pair()
        co.set(f"heat_tpu/sup/{mon.generation}/hb/1", "42", True)
        mon.step(0.0)
        clock[0] = 5.5
        mon.step(5.5)  # same value 42 for 5.5s > budget
        payload = supervision.aborted()
        self.assertIsNotNone(payload)
        self.assertEqual(payload["rank"], 1)

    def test_departed_peer_is_not_a_failure(self):
        co, clock, mon = self._armed_pair()
        co.set(f"heat_tpu/sup/{mon.generation}/bye/1", "1", True)
        clock[0] = 100.0
        mon.step(100.0)
        self.assertIsNone(supervision.aborted())

    def test_second_monitor_adopts_peer_posted_sentinel(self):
        co, clock, mon = self._armed_pair()
        # a "remote" rank posted the sentinel directly on the shared channel
        # — at the production key, which sits strictly UNDER the abort
        # prefix (directory semantics: get_dir never returns a key equal to
        # the prefix itself, on the real service or this double)
        co.set(mon.sentinel_key, json.dumps(
            {"kind": "peer-failed", "rank": 1, "last_seen_s": 9.9, "by": 1}
        ), False)
        mon.step(0.1)
        payload = supervision.aborted()
        self.assertEqual(payload["by"], 1)
        self.assertEqual(payload["last_seen_s"], 9.9)

    def test_local_coordinator_matches_real_directory_semantics(self):
        # the contract the real DistributedRuntimeService exhibits (verified
        # against jaxlib 0.4.37): dir-get returns keys strictly under the
        # prefix — NEVER one exactly equal to it — and delete removes the
        # key and its whole subtree. The double must match, or tests pass
        # on paths (sentinel adoption, barrier rank listing) that are dead
        # code in production.
        co = supervision.LocalCoordinator()
        co.set("ns/abort", "exact")
        co.set("ns/abort/0", "child")
        co.set("ns/hb/1", "7")
        self.assertEqual(co.get_dir("ns/abort"), [("ns/abort/0", "child")])
        self.assertEqual(co.get_dir("ns/abort/"), [("ns/abort/0", "child")])
        self.assertEqual(co.get_dir("ns/hb"), [("ns/hb/1", "7")])
        co.delete("ns/abort")  # directory delete: exact key + subtree
        self.assertEqual(co.get_dir("ns/abort"), [])
        self.assertEqual(co.wait("ns/hb/1", 100), "7")  # exact get still works

    def test_sentinel_roundtrip_posts_under_abort_prefix(self):
        # post_abort -> check_sentinel -> reset_abort must work through
        # directory semantics end to end: the sentinel lives below the
        # prefix and reset deletes it from the store (an armed monitor
        # would otherwise re-adopt it every tick)
        co = supervision.LocalCoordinator()
        mon = supervision.arm(co, rank=0, nprocs=2, peer_timeout_s=50.0,
                              start_thread=False)
        supervision.post_abort("peer-failed", rank=1, last_seen_s=1.0)
        self.assertEqual(len(co.get_dir(mon.abort_key)), 1)
        supervision.reset_abort()
        self.assertIsNone(supervision.aborted())
        self.assertEqual(co.get_dir(mon.abort_key), [])
        mon.check_sentinel()  # nothing left to re-adopt
        self.assertIsNone(supervision.aborted())


class TestSentinelPollOrdering(_SupervisionCase):
    def test_idle_poll_is_a_noop(self):
        supervision.poll("anything")  # disarmed AND armed-idle
        co = supervision.LocalCoordinator()
        supervision.arm(co, rank=0, nprocs=1, start_thread=False)
        supervision.poll("anything")

    def test_post_abort_then_poll_raises_each_time(self):
        supervision.arm(supervision.LocalCoordinator(), rank=0, nprocs=2,
                        start_thread=False)
        supervision.post_abort("peer-failed", rank=1, last_seen_s=3.0)
        for _ in range(3):  # fresh exception per poll, payload stable
            with self.assertRaises(resilience.PeerFailed) as ctx:
                supervision.poll("site.x")
            self.assertEqual(ctx.exception.rank, 1)

    def test_collective_timeout_payload_maps_to_typed(self):
        supervision.arm(supervision.LocalCoordinator(), rank=2, nprocs=4,
                        start_thread=False)
        supervision.post_abort("collective-timeout", site="comm.psum",
                               elapsed_s=12.5)
        with self.assertRaises(resilience.CollectiveTimeout) as ctx:
            supervision.poll()
        self.assertEqual(ctx.exception.site, "comm.psum")
        self.assertEqual(ctx.exception.elapsed_s, 12.5)
        self.assertEqual(ctx.exception.detected_by, 2)

    def test_first_sentinel_wins(self):
        supervision.arm(supervision.LocalCoordinator(), rank=0, nprocs=3,
                        start_thread=False)
        supervision.post_abort("peer-failed", rank=2, last_seen_s=1.0)
        supervision.post_abort("peer-failed", rank=1, last_seen_s=9.0)
        self.assertEqual(supervision.aborted()["rank"], 2)

    def test_communication_chokepoint_delivers_typed(self):
        # the _guarded chokepoint: a layout op must raise PeerFailed, and
        # recover after the abort clears
        supervision.arm(supervision.LocalCoordinator(), rank=0, nprocs=2,
                        start_thread=False)
        supervision.post_abort("peer-failed", rank=1, last_seen_s=2.0)
        with self.assertRaises(resilience.PeerFailed):
            ht.arange(16, split=0).parray  # noqa: B018 - forces comm.shard
        supervision.reset_abort()
        self.assertEqual(float(ht.arange(16, split=0).sum().item()), 120.0)

    def test_scheduler_predispatch_sheds_typed(self):
        # queued work behind a paused scheduler is shed with the typed abort
        # at the pre-dispatch checkpoint, and lands in the lifecycle ledger
        supervision.arm(supervision.LocalCoordinator(), rank=0, nprocs=2,
                        start_thread=False)
        sched = _executor._get_scheduler()
        self.assertTrue(sched.wait_idle(10.0))
        base = sched.stats()["lifecycle"]["shed"]
        for _ in range(2):  # past the warm-up threshold: the next force queues
            ((ht.arange(32, split=0) + 1.0) * 2.0).numpy()
        sched.pause()
        outcome = {}

        def force():
            try:
                x = ht.arange(32, split=0)
                y = (x + 1.0) * 2.0
                y.parray  # noqa: B018 - the force parks in the paused queue
                outcome["error"] = None
            except BaseException as exc:
                outcome["error"] = exc

        t = threading.Thread(target=force, daemon=True)
        try:
            t.start()
            deadline = time.monotonic() + 10.0
            while sched.depth() == 0 and time.monotonic() < deadline:
                time.sleep(0.005)
            self.assertGreater(sched.depth(), 0)
            supervision.post_abort("peer-failed", rank=1, last_seen_s=2.0)
        finally:
            sched.resume()
        t.join(timeout=30.0)
        self.assertFalse(t.is_alive(), "forced read stayed blocked")
        self.assertIsInstance(outcome["error"], resilience.PeerFailed)
        self.assertTrue(sched.wait_idle(10.0))
        self.assertGreater(sched.stats()["lifecycle"]["shed"], base)
        supervision.reset_abort()
        np.testing.assert_allclose(
            ((ht.arange(32, split=0) + 1.0) * 2.0).numpy(),
            (np.arange(32, dtype=np.float32) + 1.0) * 2.0,
        )


class TestWatchdog(_SupervisionCase):
    def _arm_watchdog(self, budget="0.25"):
        os.environ["HEAT_TPU_COLLECTIVE_TIMEOUT_S"] = budget
        supervision.reload_env_knobs()
        clock = [0.0]
        mon = supervision.arm(supervision.LocalCoordinator(), rank=0,
                              nprocs=1, peer_timeout_s=100.0,
                              clock=lambda: clock[0], start_thread=False)
        return clock, mon

    def test_overdue_window_fires_typed_with_postmortem(self):
        flight_dir = tempfile.mkdtemp(prefix="ht-sup-flight-")
        os.environ["HEAT_TPU_FLIGHT_DIR"] = flight_dir
        clock, mon = self._arm_watchdog()
        with self.assertRaises(resilience.CollectiveTimeout) as ctx:
            with supervision.watch("comm.stuck"):
                clock[0] = 1.0
                mon.watchdog_scan(1.0)  # the monitor tick during the hang
        self.assertEqual(ctx.exception.site, "comm.stuck")
        self.assertGreaterEqual(ctx.exception.elapsed_s, 1.0)
        # survivors see the sentinel as the same typed class
        payload = supervision.aborted()
        self.assertEqual(payload["kind"], "collective-timeout")
        self.assertEqual(payload["site"], "comm.stuck")
        # and the watchdog shipped its own post-mortem trigger kind
        dumps = glob.glob(os.path.join(flight_dir, "*.json"))
        self.assertTrue(any("supervision-watchdog" in d for d in dumps), dumps)
        with open(sorted(dumps)[0]) as f:
            dump = json.load(f)
        self.assertTrue(
            any(e["kind"] == "watchdog" for e in dump["events"]), dump["events"]
        )

    def test_window_disarms_on_exit(self):
        clock, mon = self._arm_watchdog()
        with supervision.watch("comm.fine"):
            clock[0] = 0.1
        clock[0] = 10.0
        mon.watchdog_scan(10.0)  # window already gone: nothing to flag
        self.assertIsNone(supervision.aborted())

    def test_watchdog_off_by_default(self):
        supervision.arm(supervision.LocalCoordinator(), rank=0, nprocs=1,
                        start_thread=False)
        self.assertEqual(supervision.collective_timeout_s(), 0.0)
        with supervision.watch("comm.cheap"):
            pass
        self.assertEqual(supervision.supervision_stats()["watch_windows"], 0)


class TestSupervisedCoordWaits(_SupervisionCase):
    def test_kv_wait_returns_value(self):
        co = supervision.LocalCoordinator()
        threading.Timer(0.1, lambda: co.set("k", "v42")).start()
        self.assertEqual(
            supervision.kv_wait("k", 5_000, site="t.kv", coordinator=co), "v42"
        )

    def test_kv_wait_exhaustion_is_typed_and_names_the_key(self):
        co = supervision.LocalCoordinator()
        t0 = time.monotonic()
        with self.assertRaises(resilience.CoordinationTimeout) as ctx:
            supervision.kv_wait("missing/key", 200, site="t.kv",
                                coordinator=co)
        self.assertLess(time.monotonic() - t0, 5.0)
        self.assertEqual(ctx.exception.key, "missing/key")
        self.assertEqual(ctx.exception.timeout_ms, 200)
        self.assertEqual(ctx.exception.site, "t.kv")

    def test_kv_wait_aborts_typed_mid_wait(self):
        # the wait must deliver PeerFailed from the sentinel well before its
        # own (long) budget — the no-hang contract
        supervision.arm(supervision.LocalCoordinator(), rank=0, nprocs=2,
                        start_thread=False)
        co = supervision.LocalCoordinator()
        threading.Timer(
            0.15, lambda: supervision.post_abort("peer-failed", rank=1,
                                                 last_seen_s=2.0)
        ).start()
        t0 = time.monotonic()
        with self.assertRaises(resilience.PeerFailed):
            supervision.kv_wait("never", 60_000, site="t.kv", coordinator=co)
        self.assertLess(time.monotonic() - t0, 30.0)

    def test_kv_barrier_names_missing_ranks(self):
        co = supervision.LocalCoordinator()
        co.set("bar/x/2", "1")  # rank 2 arrived, 1 and 3 never do
        with self.assertRaises(resilience.CoordinationTimeout) as ctx:
            supervision.kv_barrier("bar/x", nprocs=4, rank=0, timeout_ms=250,
                                   site="t.bar", coordinator=co)
        self.assertEqual(ctx.exception.waiting_on, [1, 3])

    def test_kv_barrier_missing_ranks_with_double_digit_world(self):
        # the arrived set comes from ONE directory listing of the namespace,
        # so rank 1 arriving must not read as rank 10/11 arrived (a per-rank
        # startswith probe would alias them)
        co = supervision.LocalCoordinator()
        for r in (1, 11):
            co.set(f"bar/w/{r}", "1")
        with self.assertRaises(resilience.CoordinationTimeout) as ctx:
            supervision.kv_barrier("bar/w", nprocs=12, rank=0, timeout_ms=250,
                                   site="t.bar", coordinator=co)
        self.assertEqual(ctx.exception.waiting_on,
                         [2, 3, 4, 5, 6, 7, 8, 9, 10])

    def test_kv_barrier_completes(self):
        co = supervision.LocalCoordinator()
        for r in (1, 2):
            co.set(f"bar/y/{r}", "1")
        supervision.kv_barrier("bar/y", nprocs=3, rank=0, timeout_ms=5_000,
                               site="t.bar", coordinator=co)

    def test_unified_knob_reload(self):
        os.environ["HEAT_TPU_COORD_TIMEOUT_MS"] = "12345"
        self.assertNotEqual(supervision.coord_timeout_ms(), 12345)  # memoised
        _executor.reload_env_knobs()  # the one re-read point covers supervision
        self.assertEqual(supervision.coord_timeout_ms(), 12345)


class TestPeerDeadFault(_SupervisionCase):
    def test_peer_dead_fires_hook_then_exits(self):
        calls = []
        orig_exit = resilience._peer_dead_exit
        resilience._peer_dead_exit = lambda status: calls.append(status)
        try:
            resilience.arm_fault_plan(
                [{"site": "train.step", "kind": "peer-dead", "on_call": 2}]
            )
            resilience.maybe_fault("train.step")  # call 1: nothing
            self.assertEqual(calls, [])
            with self.assertRaises(resilience.FaultInjected):
                resilience.maybe_fault("train.step")  # call 2: dies
            self.assertEqual(calls, [resilience.PEER_DEAD_EXIT_STATUS])
        finally:
            resilience._peer_dead_exit = orig_exit

    def test_rank_targeting(self):
        calls = []
        orig_exit = resilience._peer_dead_exit
        resilience._peer_dead_exit = lambda status: calls.append(status)
        try:
            resilience.set_fault_rank(0)
            resilience.arm_fault_plan([
                {"site": "s", "kind": "peer-dead", "on_call": 1, "rank": 3},
            ])
            resilience.maybe_fault("s")  # targeted at rank 3; we are rank 0
            self.assertEqual(calls, [])
            resilience.set_fault_rank(3)
            resilience.reset()
            with self.assertRaises(resilience.FaultInjected):
                resilience.maybe_fault("s")
            self.assertEqual(calls, [resilience.PEER_DEAD_EXIT_STATUS])
        finally:
            resilience._peer_dead_exit = orig_exit
            resilience.set_fault_rank(jax.process_index())

    def test_plan_validation(self):
        with self.assertRaises(ValueError):
            resilience.arm_fault_plan(
                [{"site": "s", "kind": "peer-dead", "rank": -2}]
            )
        with self.assertRaises(ValueError):
            resilience.arm_fault_plan([{"site": "s", "kind": "no-such-kind"}])


class TestRunSupervised(_SupervisionCase):
    def _manager(self):
        return checkpoint.CheckpointManager(
            tempfile.mkdtemp(prefix="ht-sup-ckpt-"), max_to_keep=8
        )

    def test_restart_restores_and_resumes(self):
        mgr = self._manager()
        tpl = {"w": ht.zeros((12,), split=0)}
        fail_once = [True]

        def step_fn(step, state):
            if step == 3 and fail_once[0]:
                fail_once[0] = False
                raise resilience.PeerFailed(1, 2.0)
            return {"w": state["w"] + 1.0}

        out = resilience.run_supervised(
            step_fn, mgr, template=tpl,
            state={"w": ht.zeros((12,), split=0)}, max_steps=6,
        )
        self.assertEqual(out["steps"], 6)
        self.assertEqual(out["restarts"], 1)
        # no step double-applied, none skipped: 6 increments exactly
        self.assertEqual(float(out["state"]["w"].sum().item()), 72.0)

    def test_budget_exhaustion_reraises_typed(self):
        mgr = self._manager()
        tpl = {"w": ht.zeros((4,), split=0)}

        def always_fails(step, state):
            raise resilience.CollectiveTimeout("comm.x", 9.0)

        t0 = time.monotonic()
        with self.assertRaises(resilience.CollectiveTimeout):
            resilience.run_supervised(
                always_fails, mgr, template=tpl,
                state={"w": ht.zeros((4,), split=0)}, max_steps=4,
                policy=resilience.Policy(max_attempts=2, backoff_base=0.01),
            )
        self.assertLess(time.monotonic() - t0, 30.0)

    def test_unrelated_errors_propagate_untouched(self):
        mgr = self._manager()

        def boom(step, state):
            raise ValueError("not a supervision failure")

        with self.assertRaises(ValueError):
            resilience.run_supervised(
                boom, mgr, template={"w": ht.zeros((4,), split=0)},
                state={"w": ht.zeros((4,), split=0)}, max_steps=2,
            )


class TestModelPoolFailover(_SupervisionCase):
    def test_on_peer_failure_sheds_typed_and_reopens(self):
        pool = ht.serving.ModelPool({"w": ht.zeros((8,), split=0)},
                                    name="failover-unit")
        pool._rebind({"w": ht.ones((8,), split=0)}, None)
        supervision.arm(supervision.LocalCoordinator(), rank=0, nprocs=2,
                        start_thread=False)
        supervision.post_abort("peer-failed", rank=1, last_seen_s=2.0)
        entry = pool.on_peer_failure(
            resilience.PeerFailed(1, 2.0), drain_timeout_s=5.0
        )
        self.assertEqual(entry["kind"], "peer-failover")
        self.assertIsNone(supervision.aborted())  # sentinel cleared
        sched = _executor._get_scheduler()
        self.assertFalse(sched.draining())  # admission reopened
        # the pool still serves its generation
        self.assertEqual(float(pool.state["w"].sum().item()), 8.0)
        ledger = pool.swap_ledger()
        self.assertEqual(ledger[-1]["kind"], "peer-failover")


class TestHLOByteParity(_SupervisionCase):
    """Armed-but-idle supervision must compile byte-identical HLO: the plane
    exists strictly OUTSIDE traced program bodies (same contract as
    resilience/profiler/telemetry)."""

    @staticmethod
    def _chain_hlos():
        _executor.clear_executor_cache()
        np_x = np.arange(8, dtype=np.float32)
        np_y = np.full(8, 0.5, dtype=np.float32)
        for _ in range(2):  # conftest's HEAT_TPU_JIT_THRESHOLD=2 warm-up
            x = ht.array(np_x, split=0)
            y = ht.array(np_y, split=0)
            (x + y).sum().parray  # noqa: B018 - forces the chain
        with _executor._lock:
            entries = [
                e for e in _executor._programs.values()
                if e is not _executor.UNSUPPORTED and e.arg_specs is not None
            ]
        texts = {}
        for entry in entries:
            fn = jax.jit(
                entry._traced(),
                out_shardings=entry.out_shardings,
                keep_unused=entry.donate_index is not None,
            )
            texts[entry.label] = fn.lower(*entry.arg_specs).compile().as_text()
        return texts

    def test_hlo_byte_parity_armed_idle(self):
        diagnostics.disable()
        baseline = self._chain_hlos()
        self.assertGreaterEqual(len(baseline), 2, list(baseline))
        os.environ["HEAT_TPU_COLLECTIVE_TIMEOUT_S"] = "30"
        supervision.reload_env_knobs()
        supervision.arm(supervision.LocalCoordinator(), rank=0, nprocs=1,
                        start_thread=False)
        try:
            armed = self._chain_hlos()
        finally:
            supervision.disarm()
            del os.environ["HEAT_TPU_COLLECTIVE_TIMEOUT_S"]
            supervision.reload_env_knobs()
        self.assertEqual(armed, baseline,
                         "arming supervision changed compiled HLO")
        again = self._chain_hlos()
        self.assertEqual(again, baseline,
                         "disarming did not restore byte-identical HLO")


if __name__ == "__main__":
    unittest.main()
