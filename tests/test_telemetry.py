"""``ht.telemetry`` tests (ISSUE 11 tentpole) — the single-process half.

Five contracts, mirroring ``heat_tpu/core/telemetry.py`` (the real
multi-process shard/merge/skew/straggler path runs in
``tests/test_multiprocess.py`` with 2- and 4-process ``jax.distributed``
jobs):

- **Collective windows**: ``MeshCommunication._guarded`` times every
  collective/layout invocation into per-(site, seq) windows and per-site
  duration histograms when collection is on, records nothing when off, and
  never changes compiled HLO either way.
- **Shard/merge math** on synthetic shards with known contents: exact counter
  sums, span folds, associativity-independent histogram quantiles, summed
  executor stats, preserved per-process breakdowns.
- **Skew & straggler attribution**: hand-built windows with known anchors
  produce the expected cross-rank skew values, ``skew.<op>`` histograms, and
  a scoreboard naming the hand-planted straggler; clock anchors shift
  per-process timestamps onto one timeline.
- **Merged trace namespacing**: every process's events land in its own
  disjoint pid range (request tracks AND counter tracks — two ranks'
  cumulative counters must never sum onto one track), timestamps are aligned
  and non-negative, and flow arrows link the same collective across process
  tracks.
- **Flight recorder**: the always-on ring records resilience/fallback/
  lifecycle events; the typed failure kinds auto-dump a post-mortem artifact
  (rate-limited, thread-offloaded); dumps and shard/report writes all go
  through ``resilience.atomic_write`` so a crash mid-dump cannot leave a
  torn artifact.
"""

import glob
import json
import os
import time
import unittest

import numpy as np

import jax

import heat_tpu as ht
from heat_tpu.core import _executor, diagnostics, profiler, resilience, telemetry
from heat_tpu.testing import TestCase


class _TelTestCase(TestCase):
    """Reset + disable the telemetry plane (and its feeders) around every
    test; give each test a fresh auto-dump budget."""

    def setUp(self):
        super().setUp()
        self._reset()

    def tearDown(self):
        self._reset()
        super().tearDown()

    def _reset(self):
        telemetry.disable()
        telemetry.reset()
        profiler.disable()
        profiler.reset()
        diagnostics.disable()
        diagnostics.reset()
        resilience.disarm_fault_plan()
        resilience.reset()
        with telemetry._lock:
            telemetry._auto_dumps = 0
            telemetry._last_auto_ns.clear()

    def _tmp(self):
        import shutil
        import tempfile

        d = tempfile.mkdtemp(prefix="ht-telemetry-test-")
        self.addCleanup(lambda: shutil.rmtree(d, ignore_errors=True))
        return d

    def _flight_env(self, path):
        old = os.environ.get("HEAT_TPU_FLIGHT_DIR")
        os.environ["HEAT_TPU_FLIGHT_DIR"] = path

        def restore():
            if old is None:
                os.environ.pop("HEAT_TPU_FLIGHT_DIR", None)
            else:
                os.environ["HEAT_TPU_FLIGHT_DIR"] = old

        self.addCleanup(restore)


def _wait_for(predicate, timeout_s=10.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.05)
    return predicate()


# --------------------------------------------------------------------------- windows
class TestCollectiveWindows(_TelTestCase):
    def test_window_seq_and_duration_histogram(self):
        with telemetry.collective_window("comm.test"):
            time.sleep(0.002)
        with telemetry.collective_window("comm.test"):
            pass
        with telemetry.collective_window("comm.other"):
            pass
        wins = telemetry.windows()
        self.assertEqual([(w[0], w[1]) for w in wins],
                         [("comm.test", 1), ("comm.test", 2), ("comm.other", 1)])
        for _, _, t0, t1, tag in wins:
            self.assertGreaterEqual(t1, t0)
            self.assertIsNone(tag)  # no ambient request scope in this test
        durs = telemetry.duration_snapshots()
        self.assertEqual(durs["comm.test"]["count"], 2)
        self.assertGreaterEqual(durs["comm.test"]["max_s"], 0.002)

    def test_seq_is_per_request_tag(self):
        # two tenants interleaving must not share a sequence: the identity
        # the merge matches on is (site, tag, seq), so ranks that interleave
        # tenants in a different order still pair the RIGHT collectives
        profiler.enable()
        with profiler.request("tenantA"):
            with telemetry.collective_window("comm.psum"):
                pass
        with profiler.request("tenantB"):
            with telemetry.collective_window("comm.psum"):
                pass
        with profiler.request("tenantA"):
            with telemetry.collective_window("comm.psum"):
                pass
        keyed = [(w[4], w[1]) for w in telemetry.windows()]
        self.assertEqual(keyed, [("tenantA", 1), ("tenantB", 1), ("tenantA", 2)])

    def test_skew_matches_by_tag_across_interleaved_ranks(self):
        # rank 0 runs A then B; rank 1 runs B then A. A bare per-site counter
        # would pair A(rank0) with B(rank1); the tag-keyed identity pairs
        # like with like and measures ~zero skew
        def win(tag, enter_us):
            return ["comm.psum", 1, enter_us * 1000, (enter_us + 5) * 1000, tag]

        shards = [
            _synthetic_shard(0, 2, anchor_ns=0,
                             windows=[win("A", 100), win("B", 9000)]),
            _synthetic_shard(1, 2, anchor_ns=0,
                             windows=[win("B", 9010), win("A", 108)]),
        ]
        skew = telemetry.merge(shards)["skew"]
        self.assertEqual(skew["collectives_measured"], 2)
        self.assertLessEqual(skew["sites"]["comm.psum"]["max_skew_us"], 20)

    def test_guarded_chokepoint_records_only_when_collecting(self):
        x = ht.array(np.arange(12, dtype=np.float32), split=0)
        self.assertEqual(telemetry.windows(), [])  # collection off: nothing
        telemetry.enable()
        y = ht.array(np.arange(12, dtype=np.float32) * 2, split=0)
        del x, y
        sites = {w[0] for w in telemetry.windows()}
        self.assertIn("comm.shard", sites)

    def test_hlo_byte_parity_with_collection_on(self):
        # same proof shape as diagnostics/profiler/resilience: nothing enters
        # traced bodies, so compiled HLO is byte-identical on/off
        def chain_hlos():
            _executor.clear_executor_cache()
            x = ht.array(np.arange(8, dtype=np.float32), split=0)
            y = ht.array(np.full(8, 0.5, dtype=np.float32), split=0)
            for _ in range(2):  # past the conftest warm-up threshold (2)
                (x + y).sum().parray
            with _executor._lock:
                entries = [
                    e for e in _executor._programs.values()
                    if e is not _executor.UNSUPPORTED and e.arg_specs is not None
                ]
            texts = {}
            for entry in entries:
                fn = jax.jit(
                    entry._traced(),
                    out_shardings=entry.out_shardings,
                    keep_unused=entry.donate_index is not None,
                )
                texts[entry.label] = fn.lower(*entry.arg_specs).compile().as_text()
            return texts

        baseline = chain_hlos()
        self.assertGreaterEqual(len(baseline), 1, list(baseline))
        telemetry.enable()
        try:
            collected = chain_hlos()
        finally:
            telemetry.disable()
        self.assertEqual(collected, baseline,
                         "telemetry collection changed compiled HLO")


# --------------------------------------------------------------------------- shards
def _synthetic_shard(index, count, *, anchor_ns, counters=None, hists=None,
                     windows=(), trace=None, executor=None):
    """A hand-built shard with exactly known contents."""
    prof = {"histograms": hists or {}, "requests_total": 0}
    diag = {
        "counters": dict(counters or {}),
        "spans": {},
        "collectives": [],
        "profiler": prof,
    }
    if executor is not None:
        diag["executor"] = executor
    return {
        "schema": telemetry.SCHEMA,
        "generated_at": "2026-08-04T00:00:00Z",
        "process": {"index": index, "count": count, "pid": 1000 + index,
                    "host": "testhost"},
        "clock": {
            "anchor_monotonic_ns": anchor_ns,
            "anchors_monotonic_ns": None,
            "aligned": True,
            "profiler_origin_monotonic_us": anchor_ns / 1e3,  # profiler t0 ==
            "dumped_at_monotonic_ns": anchor_ns + 10**9,      # the anchor
        },
        "collectives": {"windows": [list(w) for w in windows], "durations": {}},
        "flight": {"events": [], "dumps": []},
        "diagnostics": diag,
        "trace": trace or {"requests": [], "slices": [], "counter_events": []},
    }


def _hist_snap(values):
    h = profiler.Histogram()
    for v in values:
        h.observe(v)
    return h.snapshot()


class TestShardMerge(_TelTestCase):
    def test_dump_shard_roundtrip(self):
        diagnostics.enable()
        diagnostics.counter("t.mark", 7)
        profiler.enable()
        profiler.observe("t.lat", 0.01)
        out = self._tmp()
        path = telemetry.dump_shard(out)
        self.assertTrue(os.path.exists(path))
        with open(path) as f:
            shard = json.load(f)
        self.assertEqual(shard["schema"], telemetry.SCHEMA)
        self.assertEqual(shard["diagnostics"]["counters"]["t.mark"], 7)
        merged = telemetry.merge(out)
        self.assertEqual(merged["schema"], telemetry.MERGED_SCHEMA)
        self.assertEqual(merged["processes"], 1)
        self.assertEqual(merged["counters"]["t.mark"], 7)
        self.assertEqual(merged["histograms"]["t.lat"]["count"], 1)

    def test_exact_counter_sums_and_per_process_breakdown(self):
        shards = [
            _synthetic_shard(0, 3, anchor_ns=0, counters={"a": 1, "b": 10}),
            _synthetic_shard(1, 3, anchor_ns=0, counters={"a": 2}),
            _synthetic_shard(2, 3, anchor_ns=0, counters={"a": 4, "c": 0.5}),
        ]
        merged = telemetry.merge(shards)
        self.assertEqual(merged["counters"], {"a": 7, "b": 10, "c": 0.5})
        self.assertEqual(merged["processes"], 3)
        self.assertEqual(merged["per_process"]["1"]["counters"], {"a": 2})

    def test_histogram_merge_is_order_independent(self):
        rng = np.random.RandomState(5)
        streams = [rng.lognormal(-6, 1.0, 200) for _ in range(3)]
        shards = [
            _synthetic_shard(i, 3, anchor_ns=0,
                             hists={"lat": _hist_snap(streams[i])})
            for i in range(3)
        ]
        fwd = telemetry.merge(shards)["histograms"]["lat"]
        rev = telemetry.merge(list(reversed(shards)))["histograms"]["lat"]
        self.assertEqual(fwd["buckets"], rev["buckets"])
        self.assertEqual(fwd["count"], 600)
        for q in ("p50_s", "p95_s", "p99_s"):
            self.assertEqual(fwd[q], rev[q])
        # equivalent to having observed the union stream
        union = _hist_snap(np.concatenate(streams))
        self.assertEqual(fwd["buckets"], union["buckets"])

    def test_executor_stats_sum_and_peak_fold(self):
        shards = [
            _synthetic_shard(0, 2, anchor_ns=0,
                             executor={"hits": 10, "misses": 2, "draining": False,
                                       "queue_depth_peak": 10,
                                       "batch_width_hist": {"2": 3}}),
            _synthetic_shard(1, 2, anchor_ns=0,
                             executor={"hits": 5, "misses": 1, "draining": False,
                                       "queue_depth_peak": 7,
                                       "batch_width_hist": {"2": 1, "4": 2}}),
        ]
        merged = telemetry.merge(shards)
        self.assertEqual(merged["executor"]["hits"], 15)
        self.assertEqual(merged["executor"]["misses"], 3)
        self.assertEqual(merged["executor"]["batch_width_hist"],
                         {"2": 4, "4": 2})
        # peaks max-fold: no rank ever saw a depth-17 queue
        self.assertEqual(merged["executor"]["queue_depth_peak"], 10)
        self.assertIs(merged["executor"]["draining"], False)

    def test_inconsistent_process_count_rejected(self):
        shards = [
            _synthetic_shard(0, 2, anchor_ns=0),
            _synthetic_shard(1, 3, anchor_ns=0),
        ]
        with self.assertRaises(ValueError):
            telemetry.merge(shards)

    def test_merge_empty_rejected(self):
        with self.assertRaises(ValueError):
            telemetry.merge([])

    def test_duplicate_shard_list_rejected(self):
        # same contract as load_shards: rank 0 twice would double-count sums
        shard = _synthetic_shard(0, 2, anchor_ns=0, counters={"a": 1})
        with self.assertRaises(ValueError):
            telemetry.merge([shard, dict(shard)])

    def test_cli_check_gates_job_completeness(self):
        out = self._tmp()
        diagnostics.enable()
        diagnostics.counter("t.mark", 1)
        telemetry.dump_shard(out)
        # rewrite the shard to claim a 2-process job: one shard of two
        path = os.path.join(out, os.listdir(out)[0])
        with open(path) as f:
            shard = json.load(f)
        shard["process"]["count"] = 2
        with open(path, "w") as f:
            json.dump(shard, f)
        self.assertEqual(telemetry.main(["merge", "--dir", out]), 0)
        self.assertEqual(telemetry.main(["merge", "--dir", out, "--check"]), 1)


# --------------------------------------------------------------------------- skew
class TestSkewAttribution(_TelTestCase):
    def _skewed_shards(self):
        # 3 ranks; anchors deliberately far apart (different "boot offsets")
        # so only ALIGNED math can see the true skew. Rank 2 enters seq 2 of
        # comm.psum 50 ms late — the planted straggler.
        us = 1000  # ns per µs
        # window tuples: (site, seq, enter_ns, exit_ns) in each rank's OWN clock
        shards = []
        anchors = [10**12, 5 * 10**12, 9 * 10**12]
        enters_us = {  # aligned enter times per (seq, rank)
            1: [100, 110, 105],
            2: [200, 210, 50_200],   # rank 2: +50 ms
            3: [60_300, 60_290, 60_310],
        }
        for rank in range(3):
            wins = []
            for seq in (1, 2, 3):
                t0 = anchors[rank] + enters_us[seq][rank] * us
                wins.append(("comm.psum", seq, t0, t0 + 500 * us))
            shards.append(_synthetic_shard(rank, 3, anchor_ns=anchors[rank],
                                           windows=wins))
        return shards

    def test_skew_values_scoreboard_and_straggler(self):
        merged = telemetry.merge(self._skewed_shards())
        skew = merged["skew"]
        self.assertEqual(skew["collectives_measured"], 3)
        site = skew["sites"]["comm.psum"]
        self.assertEqual(site["collectives"], 3)
        self.assertAlmostEqual(site["max_skew_us"], 50_000, delta=1)
        self.assertEqual(site["max_skew_seq"], 2)
        self.assertEqual(site["slowest_rank"], 2)
        board = skew["scoreboard"]
        self.assertEqual(board["2"]["straggler_count"], 2)  # seq 2 and 3
        self.assertEqual(board["2"]["worst_site"], "comm.psum")
        self.assertEqual(board["2"]["worst_seq"], 2)
        self.assertEqual(skew["slowest_rank"], 2)
        # the skew.<op> histogram rides the merged histogram table
        self.assertIn("skew.psum", merged["histograms"])
        self.assertEqual(merged["histograms"]["skew.psum"]["count"], 3)
        self.assertGreaterEqual(merged["histograms"]["skew.psum"]["max_s"], 0.049)

    def test_single_rank_windows_have_no_skew(self):
        shards = [
            _synthetic_shard(0, 2, anchor_ns=0,
                             windows=[("comm.psum", 1, 1000, 2000)]),
            _synthetic_shard(1, 2, anchor_ns=0),
        ]
        skew = telemetry.merge(shards)["skew"]
        self.assertEqual(skew["collectives_measured"], 0)
        self.assertIsNone(skew["slowest_rank"])

    def test_unaligned_clocks_invalidate_skew_and_flows(self):
        # no handshake: per-process anchors are arbitrary boot offsets, so
        # cross-rank deltas are meaningless — no phantom straggler, no arrows
        shards = self._skewed_shards()
        for shard in shards:
            shard["clock"]["aligned"] = False
        merged = telemetry.merge(shards)
        skew = merged["skew"]
        self.assertFalse(skew["valid"])
        self.assertEqual(skew["collectives_measured"], 0)
        self.assertIsNone(skew["slowest_rank"])
        self.assertNotIn("skew.psum", merged["histograms"])
        trace = telemetry.merged_trace(shards)
        flows = [ev for ev in trace["traceEvents"]
                 if ev.get("cat") == "collective-skew"]
        self.assertEqual(flows, [])
        # aligned shards report valid attribution (the inverse contract)
        self.assertTrue(
            telemetry.merge(self._skewed_shards())["skew"]["valid"]
        )


# --------------------------------------------------------------------------- trace
class TestMergedTrace(_TelTestCase):
    def _traced_shards(self):
        trace0 = {
            "requests": [{"id": 1, "tag": "w", "t0_us": 10.0, "t1_us": 500.0}],
            "slices": [[1, 7, "request", "w", 10.0, 500.0],
                       [1, 7, "dispatch", "add", 20.0, 100.0]],
            "counter_events": [["queue_depth", 15.0, 3.0]],
        }
        trace1 = {
            "requests": [{"id": 1, "tag": "w", "t0_us": 12.0, "t1_us": 480.0}],
            "slices": [[1, 9, "request", "w", 12.0, 480.0]],
            "counter_events": [["queue_depth", 18.0, 5.0]],
        }
        s0 = _synthetic_shard(0, 2, anchor_ns=10**12, trace=trace0,
                              windows=[("comm.psum", 1, 10**12 + 50_000_000,
                                        10**12 + 51_000_000)])
        s1 = _synthetic_shard(1, 2, anchor_ns=2 * 10**12, trace=trace1,
                              windows=[("comm.psum", 1, 2 * 10**12 + 70_000_000,
                                        2 * 10**12 + 71_000_000)])
        return [s0, s1]

    def test_pid_namespacing_and_counter_tracks(self):
        obj = telemetry.merged_trace(self._traced_shards())
        self.assertEqual(obj["schema"], telemetry.TRACE_SCHEMA)
        events = obj["traceEvents"]
        stride = telemetry.PID_STRIDE
        ranges = {0: range(stride, 2 * stride), 1: range(2 * stride, 3 * stride)}
        for ev in events:
            self.assertIn(ev["pid"] // stride, (1, 2),
                          f"pid {ev['pid']} outside any process range")
        # the two ranks' queue_depth counters sit on DIFFERENT tracks (pids):
        counter_pids = {ev["pid"] for ev in events
                        if ev.get("ph") == "C" and ev["name"] == "queue_depth"}
        self.assertEqual(len(counter_pids), 2)
        self.assertTrue(any(p in ranges[0] for p in counter_pids))
        self.assertTrue(any(p in ranges[1] for p in counter_pids))
        # request tracks are namespaced with the process label
        names = {ev["args"]["name"] for ev in events
                 if ev.get("ph") == "M" and ev["name"] == "process_name"}
        self.assertIn("p0/request 1: w", names)
        self.assertIn("p1/request 1: w", names)
        self.assertIn("p0/collectives", names)

    def test_timestamps_aligned_monotone_nonnegative(self):
        obj = telemetry.merged_trace(self._traced_shards())
        events = [ev for ev in obj["traceEvents"] if "ts" in ev]
        self.assertTrue(events)
        for ev in events:
            self.assertGreaterEqual(ev["ts"], 0.0, ev)
        # per-(pid, tid) streams stay monotone for B/E pairs (nesting order)
        last = {}
        for ev in obj["traceEvents"]:
            if ev.get("ph") in ("B", "E"):
                key = (ev["pid"], ev["tid"])
                self.assertGreaterEqual(ev["ts"], last.get(key, -1.0), ev)
                last[key] = ev["ts"]
        # alignment: the two ranks' collective windows land 20 ms apart on the
        # SHARED clock even though their raw anchors differ by a full second
        xs = [ev for ev in obj["traceEvents"] if ev.get("cat") == "collective"]
        self.assertEqual(len(xs), 2)
        delta = abs(xs[0]["ts"] - xs[1]["ts"])
        self.assertAlmostEqual(delta, 20_000, delta=5)

    def test_huge_request_ids_stay_inside_pid_range(self):
        # a long-lived process's rid counter can exceed PID_STRIDE: the
        # merger renumbers densely so tracks never bleed into another
        # process's pid range (the original rid stays visible in the tag)
        big = telemetry.PID_STRIDE + 12345
        trace = {
            "requests": [{"id": big, "tag": "w", "t0_us": 1.0, "t1_us": 9.0}],
            "slices": [[big, 7, "request", "w", 1.0, 9.0]],
            "counter_events": [],
        }
        shards = [
            _synthetic_shard(0, 2, anchor_ns=0, trace=trace),
            _synthetic_shard(1, 2, anchor_ns=0),
        ]
        obj = telemetry.merged_trace(shards)
        stride = telemetry.PID_STRIDE
        for ev in obj["traceEvents"]:
            self.assertIn(ev["pid"] // stride, (1, 2), ev)
        names = {ev["args"]["name"] for ev in obj["traceEvents"]
                 if ev.get("ph") == "M" and ev["name"] == "process_name"}
        self.assertIn(f"p0/request 1: w (rid {big})", names)

    def test_flow_arrows_link_collectives_across_ranks(self):
        obj = telemetry.merged_trace(self._traced_shards())
        flows = [ev for ev in obj["traceEvents"]
                 if ev.get("cat") == "collective-skew"]
        self.assertEqual({ev["ph"] for ev in flows}, {"s", "f"})
        self.assertEqual(len({ev["pid"] for ev in flows}), 2)
        self.assertEqual({ev["name"] for ev in flows}, {"comm.psum"})


# --------------------------------------------------------------------------- flight
class TestFlightRecorder(_TelTestCase):
    def test_ring_records_and_is_bounded(self):
        for i in range(telemetry._flight.maxlen + 10):
            telemetry.flight_record("manual", f"site{i}", "d", kind="k")
        events = telemetry.flight_events()
        self.assertEqual(len(events), telemetry._flight.maxlen)
        self.assertEqual(events[-1]["site"],
                         f"site{telemetry._flight.maxlen + 9}")

    def test_fault_firing_auto_dumps_postmortem(self):
        out = os.path.join(self._tmp(), "flight")
        self._flight_env(out)
        resilience.arm_fault_plan(
            [{"site": "test.flight", "kind": "raise", "on_call": 1}]
        )
        with self.assertRaises(resilience.FaultInjected):
            resilience.maybe_fault("test.flight")
        self.assertTrue(
            _wait_for(lambda: glob.glob(os.path.join(out, "*.json"))),
            "no flight dump after an injected fault",
        )
        path = glob.glob(os.path.join(out, "*.json"))[0]
        with open(path) as f:
            dump = json.load(f)
        self.assertEqual(dump["schema"], telemetry.FLIGHT_SCHEMA)
        self.assertEqual(dump["reason"], "fault")
        self.assertTrue(any(
            e["kind"] == "fault" and e["site"] == "test.flight"
            for e in dump["events"]
        ))

    def test_breaker_open_auto_dumps(self):
        out = os.path.join(self._tmp(), "flight")
        self._flight_env(out)
        br = resilience.CircuitBreaker("test.breaker", failure_threshold=2,
                                       cooldown_s=60.0)
        br.record_failure("boom")
        br.record_failure("boom")
        self.assertEqual(br.state, resilience.OPEN)
        self.assertTrue(
            _wait_for(lambda: any("breaker-open" in p for p in
                                  glob.glob(os.path.join(out, "*.json")))),
            "no flight dump after a breaker opened",
        )

    def test_drain_timeout_kind_auto_dumps(self):
        out = os.path.join(self._tmp(), "flight")
        self._flight_env(out)
        diagnostics.record_resilience_event(
            "scheduler.drain", "drain-timeout", "synthetic"
        )
        self.assertTrue(
            _wait_for(lambda: glob.glob(os.path.join(out, "*.json"))),
            "no flight dump after a drain timeout event",
        )

    def test_auto_dump_disabled_by_env(self):
        out = os.path.join(self._tmp(), "flight")
        self._flight_env(out)
        os.environ["HEAT_TPU_FLIGHT"] = "0"
        self.addCleanup(lambda: os.environ.pop("HEAT_TPU_FLIGHT", None))
        diagnostics.record_resilience_event("x", "fault", "synthetic")
        time.sleep(0.3)
        self.assertEqual(glob.glob(os.path.join(out, "*.json")), [])
        # the ring still recorded; the on-demand dump still works
        self.assertTrue(any(e["kind"] == "fault"
                            for e in telemetry.flight_events()))
        self.assertIsNotNone(telemetry.flight_dump("on-demand"))

    def test_rate_limit_one_dump_per_trigger(self):
        out = os.path.join(self._tmp(), "flight")
        self._flight_env(out)
        for _ in range(5):
            diagnostics.record_resilience_event("x", "quarantine", "synthetic")
        self.assertTrue(_wait_for(
            lambda: glob.glob(os.path.join(out, "*.json"))))
        time.sleep(0.3)
        self.assertEqual(len(glob.glob(os.path.join(out, "*.json"))), 1)


# --------------------------------------------------------------------------- atomic dumps
class TestAtomicArtifacts(_TelTestCase):
    def test_diagnostics_dump_never_leaves_torn_artifact(self):
        path = os.path.join(self._tmp(), "diag.json")
        resilience.arm_fault_plan([
            {"site": "diagnostics.dump", "kind": "raise", "on_call": 1,
             "count": 10},
        ])
        with self.assertRaises(resilience.FaultInjected):
            diagnostics.dump(path)
        self.assertFalse(os.path.exists(path),
                         "a failed dump must not commit a partial file")
        resilience.disarm_fault_plan()
        diagnostics.dump(path)
        with open(path) as f:
            self.assertEqual(json.load(f)["schema"], diagnostics.SCHEMA)

    def test_profiler_trace_dump_is_atomic(self):
        path = os.path.join(self._tmp(), "trace.json")
        resilience.arm_fault_plan([
            {"site": "profiler.trace", "kind": "raise", "on_call": 1,
             "count": 10},
        ])
        with self.assertRaises(resilience.FaultInjected):
            profiler.dump_trace(path)
        self.assertFalse(os.path.exists(path))
        resilience.disarm_fault_plan()
        obj = profiler.dump_trace(path)
        self.assertEqual(obj["schema"], profiler.TRACE_SCHEMA)
        with open(path) as f:
            json.load(f)

    def test_shard_dump_is_atomic(self):
        out = self._tmp()
        resilience.arm_fault_plan([
            {"site": "telemetry.shard", "kind": "raise", "on_call": 1,
             "count": 10},
        ])
        with self.assertRaises(resilience.FaultInjected):
            telemetry.dump_shard(out)
        self.assertEqual(
            [n for n in os.listdir(out) if n.startswith(telemetry.SHARD_PREFIX)],
            [],
        )
        resilience.disarm_fault_plan()
        path = telemetry.dump_shard(out)
        with open(path) as f:
            self.assertEqual(json.load(f)["schema"], telemetry.SCHEMA)


# --------------------------------------------------------------------------- env knob
class TestEnvKnob(_TelTestCase):
    def test_heat_tpu_telemetry_env_enables_collection(self):
        import subprocess
        import sys

        code = (
            "from heat_tpu.core import telemetry; "
            "print('COLLECTING', telemetry.collecting())"
        )
        env = dict(os.environ)
        env["HEAT_TPU_TELEMETRY"] = "1"
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=240, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        self.assertEqual(proc.returncode, 0, proc.stderr[-2000:])
        self.assertIn("COLLECTING True", proc.stdout)


if __name__ == "__main__":
    unittest.main()


# ----------------------------------------------------------------- sequence gate
class TestSequenceConsistency(_TelTestCase):
    """The runtime twin of the static ``spmd-divergent-collective`` rule:
    ``merge`` compares every rank's per-tag ordered site list against the
    lowest rank and ``--check`` fails naming the first diverging rank/site."""

    def _win(self, site, seq, t, tag=None):
        return (site, seq, t, t + 1000, tag)

    def test_consistent_sequences_pass(self):
        wins = [self._win("comm.shard", i + 1, i * 10_000) for i in range(3)]
        shards = [
            _synthetic_shard(0, 2, anchor_ns=0, windows=wins),
            _synthetic_shard(1, 2, anchor_ns=0, windows=wins),
        ]
        seq = telemetry.merge(shards)["sequence"]
        self.assertTrue(seq["valid"])
        self.assertTrue(seq["consistent"])
        self.assertEqual(seq["windows_checked"], 6)
        self.assertEqual(seq["divergences"], [])

    def test_extra_collective_names_rank_and_site(self):
        base = [self._win("comm.shard", i + 1, i * 10_000) for i in range(3)]
        extra = base + [self._win("comm.shard", 4, 40_000)]
        shards = [
            _synthetic_shard(0, 2, anchor_ns=0, windows=base),
            _synthetic_shard(1, 2, anchor_ns=0, windows=extra),
        ]
        seq = telemetry.merge(shards)["sequence"]
        self.assertFalse(seq["consistent"])
        d = seq["divergences"][0]
        self.assertEqual(d["rank"], 1)
        self.assertEqual(d["reference_rank"], 0)
        self.assertEqual(d["index"], 3)
        self.assertIsNone(d["expected"])
        self.assertEqual(d["actual"], "comm.shard")
        self.assertEqual((d["expected_len"], d["actual_len"]), (3, 4))

    def test_mid_sequence_site_mismatch(self):
        a = [self._win("comm.shard", 1, 0), self._win("comm.psum", 1, 10_000)]
        b = [self._win("comm.shard", 1, 0), self._win("comm.all_gather", 1, 10_000)]
        shards = [
            _synthetic_shard(0, 2, anchor_ns=0, windows=a),
            _synthetic_shard(1, 2, anchor_ns=0, windows=b),
        ]
        d = telemetry.merge(shards)["sequence"]["divergences"][0]
        self.assertEqual(d["index"], 1)
        self.assertEqual(d["expected"], "comm.psum")
        self.assertEqual(d["actual"], "comm.all_gather")

    def test_tag_keyed_identity_tolerates_tenant_interleaving(self):
        # tenant A then B on rank 0; B then A on rank 1 — per-tag sequences
        # are identical, so concurrent tenants interleaving differently per
        # process must NOT read as divergence (the async executor's default)
        r0 = [self._win("comm.psum", 1, 0, "A"), self._win("comm.shard", 1, 10_000, "B")]
        r1 = [self._win("comm.shard", 1, 0, "B"), self._win("comm.psum", 1, 10_000, "A")]
        shards = [
            _synthetic_shard(0, 2, anchor_ns=0, windows=r0),
            _synthetic_shard(1, 2, anchor_ns=0, windows=r1),
        ]
        seq = telemetry.merge(shards)["sequence"]
        self.assertTrue(seq["consistent"], seq["divergences"])
        self.assertEqual(seq["tags_checked"], 2)

    def test_sequence_checked_even_with_unaligned_clocks(self):
        # the skew math refuses unaligned clocks; the sequence gate needs
        # only per-rank LOCAL ordering, so it still detects the divergence
        shards = [
            _synthetic_shard(0, 2, anchor_ns=0,
                             windows=[self._win("comm.shard", 1, 0)]),
            _synthetic_shard(1, 2, anchor_ns=999,
                             windows=[self._win("comm.psum", 1, 0)]),
        ]
        for s in shards:
            s["clock"]["aligned"] = False
        merged = telemetry.merge(shards)
        self.assertFalse(merged["skew"]["valid"])
        self.assertFalse(merged["sequence"]["consistent"])

    def test_overflowed_window_ring_invalidates_and_check_fails_loudly(self):
        import contextlib
        import io

        wins = [self._win("comm.shard", i + 1, i * 1000) for i in range(3)]
        shards = [
            _synthetic_shard(0, 2, anchor_ns=0, windows=wins),
            _synthetic_shard(1, 2, anchor_ns=0, windows=wins[:2]),
        ]
        for s in shards:
            s["collectives"]["windows_cap"] = 3
        seq = telemetry.merge(shards)["sequence"]
        self.assertFalse(seq["valid"])
        self.assertIn("HEAT_TPU_TELEMETRY_WINDOWS", seq["reason"])
        self.assertTrue(seq["consistent"])  # no confident phantom divergence
        # a gate that cannot check must not pass as one that checked: the
        # CLI --check FAILS, and the summary never affirms consistency
        d = self._tmp()
        for s in shards:
            p = os.path.join(
                d, f"{telemetry.SHARD_PREFIX}p{s['process']['index']:04d}.json"
            )
            with open(p, "w") as f:
                json.dump(s, f)
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = telemetry.main(["merge", "--dir", d, "--check"])
        out = buf.getvalue()
        self.assertEqual(rc, 1, out)
        self.assertIn("could not run", out)
        self.assertIn('"sequence_consistent": null', out)
        # report-only mode still merges
        self.assertEqual(telemetry.main(["merge", "--dir", d]), 0)

    def test_windows_capacity_env_knob_applies_at_reset(self):
        old = os.environ.get("HEAT_TPU_TELEMETRY_WINDOWS")
        os.environ["HEAT_TPU_TELEMETRY_WINDOWS"] = "300"

        def restore():
            if old is None:
                os.environ.pop("HEAT_TPU_TELEMETRY_WINDOWS", None)
            else:
                os.environ["HEAT_TPU_TELEMETRY_WINDOWS"] = old
            telemetry.reset()

        self.addCleanup(restore)
        telemetry.reset()
        self.assertEqual(telemetry._windows.maxlen, 300)
        payload = telemetry.shard_payload()
        self.assertEqual(payload["collectives"]["windows_cap"], 300)

    def test_single_shard_trivially_consistent(self):
        shards = [_synthetic_shard(0, 1, anchor_ns=0,
                                   windows=[self._win("comm.shard", 1, 0)])]
        seq = telemetry.merge(shards)["sequence"]
        self.assertTrue(seq["valid"])
        self.assertTrue(seq["consistent"])

    def test_cli_check_fails_on_divergence_and_passes_clean(self):
        import contextlib
        import io

        base = [self._win("comm.shard", 1, 0)]
        extra = base + [self._win("comm.ppermute", 1, 5_000)]

        def write_dir(shards):
            d = self._tmp()
            for s in shards:
                path = os.path.join(
                    d, f"{telemetry.SHARD_PREFIX}p{s['process']['index']:04d}.json"
                )
                with open(path, "w") as f:
                    json.dump(s, f)
            return d

        clean = write_dir([
            _synthetic_shard(0, 2, anchor_ns=0, windows=base),
            _synthetic_shard(1, 2, anchor_ns=0, windows=base),
        ])
        self.assertEqual(
            telemetry.main(["merge", "--dir", clean, "--expect", "2",
                            "--check"]), 0)

        bad = write_dir([
            _synthetic_shard(0, 2, anchor_ns=0, windows=base),
            _synthetic_shard(1, 2, anchor_ns=0, windows=extra),
        ])
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = telemetry.main(["merge", "--dir", bad, "--expect", "2",
                                 "--check"])
        out = buf.getvalue()
        self.assertEqual(rc, 1, out)
        self.assertIn("rank 1", out)
        self.assertIn("comm.ppermute", out)
        # report-only mode still merges (the gate is --check's)
        self.assertEqual(telemetry.main(["merge", "--dir", bad]), 0)
