"""Tiling tests (reference heat/core/tests/test_tiling.py): tile grids must cover the
matrix exactly, give numpy-identical views, and drive the QR panel schedule."""

import numpy as np

import heat_tpu as ht
from heat_tpu.testing import TestCase


class TestSplitTiles(TestCase):
    def test_grid_covers_array(self):
        np_x = np.arange(11 * 6, dtype=np.float32).reshape(11, 6)
        x = ht.array(np_x, split=0)
        tiles = ht.tiling.SplitTiles(x)
        dims = tiles.tile_dimensions
        self.assertEqual(dims.shape, (2, self.world_size))
        # extents along each axis sum to the global shape
        self.assertEqual(int(dims[0].sum()), 11)
        self.assertEqual(int(dims[1].sum()), 6)
        np.testing.assert_array_equal(tiles.tile_ends_g[:, -1], [11, 6])

    def test_views_match_numpy(self):
        np_x = np.arange(12 * 8, dtype=np.float32).reshape(12, 8)
        x = ht.array(np_x, split=0)
        tiles = ht.tiling.SplitTiles(x)
        ends_r = tiles.tile_ends_g[0]
        ends_c = tiles.tile_ends_g[1]
        for i in range(self.world_size):
            r0 = 0 if i == 0 else int(ends_r[i - 1])
            np.testing.assert_array_equal(np.asarray(tiles[i]), np_x[r0 : int(ends_r[i])])
            for j in range(self.world_size):
                c0 = 0 if j == 0 else int(ends_c[j - 1])
                np.testing.assert_array_equal(
                    np.asarray(tiles[i, j]), np_x[r0 : int(ends_r[i]), c0 : int(ends_c[j])]
                )

    def test_setitem(self):
        np_x = np.zeros((8, 4), np.float32)
        x = ht.array(np_x, split=0)
        tiles = ht.tiling.SplitTiles(x)
        block = np.asarray(tiles[0]).copy()
        tiles[0] = np.full_like(block, 9.0)
        self.assertTrue(np.all(np.asarray(tiles[0]) == 9.0))
        np_x[: block.shape[0]] = 9.0
        self.assert_array_equal(x, np_x)


class TestSquareDiagTiles(TestCase):
    def test_square_diagonal(self):
        m = self.world_size * 6
        np_x = np.arange(m * 4, dtype=np.float32).reshape(m, 4)
        x = ht.array(np_x, split=0)
        for tpp in (1, 2, 3):
            tiles = ht.tiling.SquareDiagTiles(x, tiles_per_proc=tpp)
            self.assertEqual(tiles.tile_rows, self.world_size * tpp)
            # diagonal tiles are square until the columns run out
            for t in range(min(tiles.tile_rows, tiles.tile_columns) - 1):
                h, w = tiles.get_tile_size((t, t))
                self.assertEqual(h, w, f"diag tile {t} not square (tpp={tpp})")
            # row starts are sorted and start at 0
            self.assertEqual(tiles.row_indices[0], 0)
            self.assertEqual(sorted(tiles.row_indices), tiles.row_indices)

    def test_get_set_tile(self):
        np_x = np.arange(16 * 16, dtype=np.float32).reshape(16, 16)
        x = ht.array(np_x, split=0)
        tiles = ht.tiling.SquareDiagTiles(x, tiles_per_proc=1)
        i, j = 0, 1
        r0, c0 = tiles.row_indices[i], tiles.col_indices[j]
        h, w = tiles.get_tile_size((i, j))
        np.testing.assert_array_equal(np.asarray(tiles[i, j]), np_x[r0 : r0 + h, c0 : c0 + w])
        tiles[i, j] = np.zeros((h, w), np.float32)
        np_x[r0 : r0 + h, c0 : c0 + w] = 0.0
        self.assert_array_equal(x, np_x)

    def test_tile_map_ownership(self):
        x = ht.zeros((self.world_size * 4, 8), split=0)
        tiles = ht.tiling.SquareDiagTiles(x, tiles_per_proc=2)
        tmap = tiles.tile_map
        self.assertEqual(tmap.shape, (tiles.tile_rows, tiles.tile_columns))
        # two consecutive tile rows per shard
        for i in range(tiles.tile_rows):
            self.assertTrue(np.all(tmap[i] == min(i // 2, self.world_size - 1)))

    def test_errors(self):
        with self.assertRaises(TypeError):
            ht.tiling.SquareDiagTiles(np.zeros((4, 4)))
        with self.assertRaises(ValueError):
            ht.tiling.SquareDiagTiles(ht.zeros((2, 2, 2)))
        with self.assertRaises(ValueError):
            ht.tiling.SquareDiagTiles(ht.zeros((4, 4)), tiles_per_proc=0)


class TestQRTiles(TestCase):
    def test_qr_tiles_per_proc(self):
        """tiles_per_proc changes the TSQR panel schedule, never the answer."""
        rng = np.random.default_rng(1)
        m = max(self.world_size * 24, 48)
        np_x = rng.standard_normal((m, 6)).astype(np.float32)
        x = ht.array(np_x, split=0)
        for tpp in (1, 2, 4):
            q, r = ht.linalg.qr(x, tiles_per_proc=tpp)
            np.testing.assert_allclose(
                (q @ r).numpy(), np_x, atol=1e-4, err_msg=f"tpp={tpp}"
            )
            qn = q.numpy()
            np.testing.assert_allclose(qn.T @ qn, np.eye(qn.shape[1]), atol=1e-4)
        with self.assertRaises(ValueError):
            ht.linalg.qr(x, tiles_per_proc=0)


if __name__ == "__main__":
    import unittest

    unittest.main()
