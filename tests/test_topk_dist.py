"""Distributed top-k (VERDICT r4 #6): per-shard top-k + P·k candidate gather along
the split dim — the reference's ``mpi_topk`` candidate-reduction
(``/root/reference/heat/core/manipulations.py:3982,4137``) on XLA collectives.
Memory proof mirrors tests/test_dist_sort.py: no full-size buffer per device."""

import unittest

import numpy as np

import jax
import jax.numpy as jnp

import heat_tpu as ht
from heat_tpu.core.dndarray import DNDarray


def np_topk(a, k, axis, largest):
    """Oracle with the framework's tie rule: lowest original index wins."""
    am = np.moveaxis(a, axis, -1)
    if largest:
        if np.issubdtype(am.dtype, np.integer):
            # negation can overflow the input dtype: lexsort (value desc, index asc)
            # on a widened copy per row
            flat = am.reshape(-1, am.shape[-1])
            order = np.stack(
                [np.lexsort((np.arange(r.size), -r.astype(np.int64))) for r in flat]
            ).reshape(am.shape)
        else:
            order = np.argsort(-am.astype(np.float64), axis=-1, kind="stable")
        idx = order[..., :k]
    else:
        idx = np.argsort(am, axis=-1, kind="stable")[..., :k]
    vals = np.take_along_axis(am, idx, axis=-1)
    return np.moveaxis(vals, -1, axis), np.moveaxis(idx, -1, axis)


class TestDistributedTopk(unittest.TestCase):
    @property
    def comm(self):
        return ht.core.communication.get_comm()

    def check(self, a, k, dim, largest):
        x = ht.array(a, split=dim)
        v, i = ht.topk(x, k, dim=dim, largest=largest)
        wv, wi = np_topk(a, k, dim, largest)
        np.testing.assert_array_equal(v.numpy(), wv, err_msg=f"values k={k} dim={dim} largest={largest}")
        np.testing.assert_array_equal(i.numpy(), wi, err_msg=f"indices k={k} dim={dim} largest={largest}")

    def test_1d_float(self):
        P = self.comm.size
        rng = np.random.default_rng(0)
        for n in (16 * P, 16 * P + 3):  # divisible and ragged
            a = rng.standard_normal(n).astype(np.float32)
            for k in (1, 5, 16):
                for largest in (True, False):
                    self.check(a, k, 0, largest)

    def test_ties_match_global_tie_rule(self):
        P = self.comm.size
        n = 8 * P + 1
        a = np.asarray([1.0, 2.0] * (n // 2) + [2.0], np.float32)  # heavy duplicates
        self.check(a, 5, 0, True)
        self.check(a, 5, 0, False)

    def test_int_extremes_and_unsigned(self):
        P = self.comm.size
        n = 8 * P
        rng = np.random.default_rng(1)
        ai = rng.integers(-50, 50, n).astype(np.int32)
        ai[[0, 3]] = np.iinfo(np.int32).min  # negation would overflow these
        ai[[5, 9]] = np.iinfo(np.int32).max
        for largest in (True, False):
            self.check(ai, 6, 0, largest)
        au = rng.integers(0, 100, n).astype(np.uint8)  # heat's one unsigned dtype
        for largest in (True, False):
            self.check(au, 4, 0, largest)

    def test_2d_both_dims(self):
        P = self.comm.size
        rng = np.random.default_rng(2)
        a = rng.standard_normal((4 * P + 2, 6)).astype(np.float32)
        self.check(a, 3, 0, True)   # split dim
        self.check(a, 3, 0, False)
        x = ht.array(a, split=0)    # topk along NON-split dim stays per-shard local
        v, i = ht.topk(x, 2, dim=1)
        wv, wi = np_topk(a, 2, 1, True)
        np.testing.assert_array_equal(v.numpy(), wv)
        np.testing.assert_array_equal(i.numpy(), wi)

    def test_k_larger_than_shard_falls_back(self):
        P = self.comm.size
        n = 4 * P
        a = np.random.default_rng(3).standard_normal(n).astype(np.float32)
        self.check(a, n - 1, 0, True)  # k > c: global fallback still correct

    def test_out_param(self):
        P = self.comm.size
        n = 8 * P
        a = np.random.default_rng(4).standard_normal(n).astype(np.float32)
        x = ht.array(a, split=0)
        v0, i0 = ht.topk(x, 3)
        out_v = ht.zeros(3, dtype=ht.float32)
        out_i = ht.zeros(3, dtype=ht.int64)
        v, i = ht.topk(x, 3, out=(out_v, out_i))
        np.testing.assert_array_equal(v.numpy(), v0.numpy())
        np.testing.assert_array_equal(out_v.numpy(), v0.numpy())
        np.testing.assert_array_equal(out_i.numpy(), i0.numpy())

    def test_compiles_shard_local(self):
        comm = self.comm
        P = comm.size
        if P == 1 or comm.mesh is None:
            self.skipTest("needs a distributed mesh")
        n = 8192 * P + 3
        c = -(-n // P)
        k = 16
        x = ht.array(np.random.default_rng(5).standard_normal(n).astype(np.float32), split=0)

        def f(p):
            d = DNDarray(p, (n,), ht.float32, 0, x.device, comm, True)
            v, i = ht.topk(d, k)
            return v.larray, i.larray

        compiled = jax.jit(f).lower(x.parray).compile()
        hlo = compiled.as_text()
        ma = compiled.memory_analysis()
        shard_bytes = c * 4
        global_bytes = n * 4
        # the only gather is the P*k candidate exchange, not the array
        self.assertLess(ma.temp_size_in_bytes, global_bytes)
        self.assertLessEqual(ma.argument_size_in_bytes, 2 * shard_bytes)
        v, i = f(x.parray)
        wv, wi = np_topk(np.asarray(jax.device_get(x.larray)), k, 0, True)
        np.testing.assert_array_equal(np.asarray(v), wv)
        np.testing.assert_array_equal(np.asarray(i), wi)


if __name__ == "__main__":
    unittest.main()
