"""vision_transforms tests (reference heat/utils/tests: the passthrough is tested via
torchvision; here the native transforms are checked against numpy directly)."""

import numpy as np

import jax

import heat_tpu as ht
from heat_tpu.utils import vision_transforms as T
from heat_tpu.testing import TestCase


class TestVisionTransforms(TestCase):
    def setUp(self):
        rng = np.random.default_rng(0)
        self.batch = rng.integers(0, 256, (8, 3, 16, 16)).astype(np.uint8)

    def test_to_tensor(self):
        out = T.ToTensor()(self.batch)
        self.assertEqual(out.dtype, np.float32)
        np.testing.assert_allclose(np.asarray(out), self.batch / 255.0, rtol=1e-6)

    def test_normalize(self):
        x = self.batch.astype(np.float32)
        mean, std = [1.0, 2.0, 3.0], [2.0, 2.0, 2.0]
        out = np.asarray(T.Normalize(mean, std)(x))
        expected = (x - np.reshape(mean, (3, 1, 1))) / np.reshape(std, (3, 1, 1))
        np.testing.assert_allclose(out, expected, rtol=1e-6)
        # 2-D grayscale: scalar mean/std
        g = x[0, 0]
        np.testing.assert_allclose(
            np.asarray(T.Normalize(5.0, 2.0)(g)), (g - 5.0) / 2.0, rtol=1e-6
        )

    def test_flips(self):
        x = self.batch.astype(np.float32)
        always = T.RandomHorizontalFlip(1.0)(x, key=jax.random.key(0))
        np.testing.assert_array_equal(np.asarray(always), x[..., ::-1])
        never = T.RandomHorizontalFlip(0.0)(x, key=jax.random.key(0))
        np.testing.assert_array_equal(np.asarray(never), x)
        vert = T.RandomVerticalFlip(1.0)(x, key=jax.random.key(0))
        np.testing.assert_array_equal(np.asarray(vert), x[..., ::-1, :])
        # per-sample decision for batches: p=0.5 flips some, not all
        T.seed(3)
        some = np.asarray(T.RandomHorizontalFlip(0.5)(x))
        flipped = [not np.array_equal(some[i], x[i]) for i in range(len(x))]
        self.assertTrue(any(flipped) and not all(flipped))

    def test_crops(self):
        x = self.batch.astype(np.float32)
        out = T.RandomCrop(8)(x, key=jax.random.key(1))
        self.assertEqual(np.asarray(out).shape, (8, 3, 8, 8))
        out = T.RandomCrop(16, padding=2)(x, key=jax.random.key(1))
        self.assertEqual(np.asarray(out).shape, (8, 3, 16, 16))
        cc = np.asarray(T.CenterCrop(8)(x))
        np.testing.assert_array_equal(cc, x[:, :, 4:12, 4:12])

    def test_resize(self):
        x = self.batch.astype(np.float32)
        out = np.asarray(T.Resize((8, 8))(x))
        self.assertEqual(out.shape, (8, 3, 8, 8))
        # constant image stays constant under bilinear resize
        const = np.full((3, 16, 16), 7.0, np.float32)
        np.testing.assert_allclose(np.asarray(T.Resize(4)(const)), 7.0, rtol=1e-5)

    def test_compose_and_dndarray(self):
        pipeline = T.Compose(
            [T.ToTensor(), T.Normalize([0.5] * 3, [0.5] * 3), T.CenterCrop(8)]
        )
        out = np.asarray(pipeline(self.batch))
        self.assertEqual(out.shape, (8, 3, 8, 8))
        # DNDarray in → DNDarray out, split preserved on the batch axis
        hx = ht.array(self.batch, split=0)
        hout = pipeline(hx)
        self.assertIsInstance(hout, ht.DNDarray)
        self.assertEqual(hout.split, 0)
        np.testing.assert_allclose(hout.numpy(), out, rtol=1e-5)
        self.assertIn("Compose", repr(pipeline))

    def test_errors(self):
        with self.assertRaises(ValueError):
            T.CenterCrop(4)(np.zeros((2, 2, 2, 2, 2), np.float32))


if __name__ == "__main__":
    import unittest

    unittest.main()
